"""Human-name detection and name-entity tagging.

Parity targets:
- ``core/.../stages/impl/feature/HumanNameDetector.scala`` +
  ``core/.../utils/stages/NameDetectUtils.scala``: estimator that decides
  whether a Text column holds person names (dictionary hit-rate averaged
  over rows >= threshold), then per-row emits a NameStats map
  (isName/originalValue/gender) using an ordered list of gender-detection
  strategies (honorific scan, token index, last token).
- ``core/.../stages/impl/feature/NameEntityRecognizer.scala`` + OpenNLP
  tagger: Text -> MultiPickListMap of token -> entity tags.

The reference ships OpenNLP binary models + large census dictionaries; this
build uses compact built-in first-name/gender/honorific dictionaries (the
detection *mechanism* — monoid stats, threshold decision, strategy ordering,
sensitive-feature surfacing — is the parity contract, the dictionary is a
swappable resource). Host stages: string work stays off the device.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Optional, Sequence

from transmogrifai_tpu.stages.base import Estimator, HostTransformer
from transmogrifai_tpu.types import feature_types as ft

__all__ = ["GenderDetectStrategy", "HumanNameDetector",
           "HumanNameDetectorModel", "NameEntityRecognizer",
           "MALE_NAMES", "FEMALE_NAMES", "NAME_DICTIONARY"]

_TOKEN_RE = re.compile(r"[^\W\d_]+", re.UNICODE)

MALE_NAMES = frozenset(
    "james john robert michael william david richard joseph thomas charles "
    "christopher daniel matthew anthony mark donald steven paul andrew "
    "joshua kenneth kevin brian george timothy ronald edward jason jeffrey "
    "ryan jacob gary nicholas eric jonathan stephen larry justin scott "
    "brandon benjamin samuel gregory frank alexander raymond patrick jack "
    "dennis jerry tyler aaron jose adam nathan henry douglas zachary peter "
    "kyle noah ethan carlos juan luis miguel pedro diego omar ali ahmed "
    "mohammed wei jun hiroshi kenji ivan dmitri sergei pierre jean luc "
    "hans klaus giovanni marco antonio".split())

FEMALE_NAMES = frozenset(
    "mary patricia jennifer linda elizabeth barbara susan jessica sarah "
    "karen lisa nancy betty margaret sandra ashley kimberly emily donna "
    "michelle carol amanda dorothy melissa deborah stephanie rebecca sharon "
    "laura cynthia kathleen amy angela shirley anna brenda pamela emma "
    "nicole helen samantha katherine christine debra rachel carolyn janet "
    "catherine maria heather diane ruth julie olivia joyce virginia grace "
    "sofia isabella mia charlotte amelia harper luna camila elena fatima "
    "aisha mei yuki sakura ingrid anastasia natasha marie claire chloe "
    "giulia francesca".split())

NAME_DICTIONARY = MALE_NAMES | FEMALE_NAMES

MALE_HONORIFICS = frozenset({"mr", "mister", "sir"})
FEMALE_HONORIFICS = frozenset({"ms", "mrs", "miss", "madam"})


def _tokens(value: Optional[str]) -> list[str]:
    if not value:
        return []
    return [t.lower() for t in _TOKEN_RE.findall(value)]


@dataclass(frozen=True)
class GenderDetectStrategy:
    """Serializable gender strategy (reference GenderDetectStrategy ADT):
    kind in {FindHonorific, ByIndex, ByLast}; ByIndex carries the token
    index."""

    kind: str = "FindHonorific"
    index: int = 0

    def detect(self, tokens: Sequence[str]) -> str:
        """-> 'Male' | 'Female' | 'GenderNA'."""
        if self.kind == "FindHonorific":
            for t in tokens:
                if t in MALE_HONORIFICS:
                    return "Male"
                if t in FEMALE_HONORIFICS:
                    return "Female"
            return "GenderNA"
        if self.kind == "ByIndex":
            toks = [t for t in tokens if t not in MALE_HONORIFICS
                    and t not in FEMALE_HONORIFICS]
            if self.index < len(toks):
                return _gender_of(toks[self.index])
            return "GenderNA"
        if self.kind == "ByLast":
            return _gender_of(tokens[-1]) if tokens else "GenderNA"
        return "GenderNA"

    def key(self) -> str:
        return (f"ByIndex({self.index})" if self.kind == "ByIndex"
                else f"{self.kind}()")


def _gender_of(token: str) -> str:
    if token in MALE_NAMES:
        return "Male"
    if token in FEMALE_NAMES:
        return "Female"
    return "GenderNA"


DEFAULT_STRATEGIES = (
    GenderDetectStrategy("FindHonorific"),
    GenderDetectStrategy("ByIndex", 0),
    GenderDetectStrategy("ByLast"),
)


@dataclass
class NameDetectStats:
    """Monoid of per-column name evidence (reference NameDetectStats):
    averaged dictionary hit fraction + per-strategy gender tallies."""

    count: int = 0
    dict_hits: float = 0.0
    gender_counts: dict = field(default_factory=dict)  # strategy -> [m, f, na]

    def add(self, value: Optional[str],
            strategies: Sequence[GenderDetectStrategy]) -> None:
        toks = _tokens(value)
        if not toks:
            return
        self.count += 1
        self.dict_hits += sum(
            1 for t in toks if t in NAME_DICTIONARY) / len(toks)
        for s in strategies:
            tally = self.gender_counts.setdefault(s.key(), [0, 0, 0])
            g = s.detect(toks)
            tally[0 if g == "Male" else 1 if g == "Female" else 2] += 1

    def merge(self, other: "NameDetectStats") -> "NameDetectStats":
        self.count += other.count
        self.dict_hits += other.dict_hits
        for k, v in other.gender_counts.items():
            t = self.gender_counts.setdefault(k, [0, 0, 0])
            for i in range(3):
                t[i] += v[i]
        return self

    @property
    def predicted_name_prob(self) -> float:
        return self.dict_hits / self.count if self.count else 0.0


class HumanNameDetector(Estimator):
    """Text -> NameStats. Fit decides treat-as-name and orders gender
    strategies by how often they resolved a gender (fewest GenderNA first,
    mirroring the reference's orderGenderStrategies)."""

    in_types = (ft.Text,)
    out_type = ft.NameStats

    def __init__(self, threshold: float = 0.5, uid: Optional[str] = None):
        self.threshold = float(threshold)
        super().__init__(uid=uid)

    def fit_model(self, data) -> "HumanNameDetectorModel":
        col = data.host_col(self.input_names[0])
        stats = NameDetectStats()
        for v in col.values:
            stats.add(v, DEFAULT_STRATEGIES)
        treat = stats.predicted_name_prob >= self.threshold
        ordered: list[GenderDetectStrategy] = []
        if treat:
            def na_count(s: GenderDetectStrategy) -> int:
                return stats.gender_counts.get(s.key(), [0, 0, 0])[2]
            ordered = sorted(DEFAULT_STRATEGIES, key=na_count)
        model = HumanNameDetectorModel(
            treat_as_name=treat,
            strategies=[{"kind": s.kind, "index": s.index} for s in ordered])
        model.metadata = {
            "treatAsName": treat,
            "predictedNameProb": stats.predicted_name_prob,
            "genderResultsByStrategy": dict(stats.gender_counts),
        }
        return model


class HumanNameDetectorModel(HostTransformer):
    in_types = (ft.Text,)
    out_type = ft.NameStats

    def __init__(self, treat_as_name: bool = False,
                 strategies: Sequence[dict] = (),
                 uid: Optional[str] = None):
        self.treat_as_name = bool(treat_as_name)
        self.strategies = [dict(s) for s in strategies]
        self.metadata: Optional[dict] = None
        super().__init__(uid=uid)

    def transform_row(self, value):
        if not self.treat_as_name:
            return {}
        toks = _tokens(value)
        if not toks:
            return {}  # a missing value is not a detected name
        gender = "GenderNA"
        for s in self.strategies:
            g = GenderDetectStrategy(s["kind"], s.get("index", 0)).detect(toks)
            if g != "GenderNA":
                gender = g
                break
        return {"isName": "true", "originalValue": value or "",
                "gender": gender}


class NameEntityRecognizer(HostTransformer):
    """Text -> MultiPickListMap token -> {entity tags}.

    The reference runs OpenNLP's binary NER models per sentence; here a
    dictionary/heuristic tagger: capitalized tokens in the name dictionary
    tag as Person (capitalization distinguishes 'Mark asked' from 'mark the
    date' — same disambiguation role the statistical model plays)."""

    in_types = (ft.Text,)
    out_type = ft.MultiPickListMap

    def __init__(self, require_capitalized: bool = True,
                 uid: Optional[str] = None):
        self.require_capitalized = bool(require_capitalized)
        super().__init__(uid=uid)

    def transform_row(self, value):
        if not value:
            return {}
        out: dict[str, set] = {}
        for raw in _TOKEN_RE.findall(value):
            if self.require_capitalized and not raw[:1].isupper():
                continue
            if raw.lower() in NAME_DICTIONARY:
                out.setdefault(raw.lower(), set()).add("Person")
        return out
