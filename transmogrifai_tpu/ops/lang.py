"""Language identification: character n-gram profiles over ~30 languages.

Parity: reference ``utils/text/Language.scala`` + the Optimaize detector
behind ``TextTokenizer.scala``/``LangDetector.scala`` — the classic textcat
"out-of-place" method (Cavnar & Trenkle 1994, the same family Optimaize
implements): each language gets a rank-ordered profile of its most frequent
character 1-3-grams built from embedded seed text; a document is scored by
how far its own top n-grams sit from each profile's ranks. Unicode script
detection short-circuits the single-script languages (Hangul, kana, Han,
Greek, Hebrew, Thai, Devanagari) before the n-gram vote, which then mostly
separates languages sharing a script (Latin, Cyrillic, Arabic).

Profiles are built once at import from the seed corpus below (a few
sentences of ordinary prose per language — written for this module, no
external data).
"""

from __future__ import annotations

import unicodedata
from collections import Counter
from typing import Optional

__all__ = ["detect_language_ngram", "language_scores", "LANGUAGES"]

#: seed prose per ISO-639-1 code
_SAMPLES: dict[str, str] = {
    "en": ("the quick brown fox jumps over the lazy dog and the weather "
           "today is rather pleasant because we are going to the market "
           "with our friends who have been waiting for this day"),
    "fr": ("le renard brun saute par dessus le chien paresseux et le temps "
           "aujourd'hui est plutôt agréable parce que nous allons au marché "
           "avec nos amis qui attendaient ce jour depuis longtemps"),
    "de": ("der schnelle braune fuchs springt über den faulen hund und das "
           "wetter ist heute ziemlich angenehm weil wir mit unseren "
           "freunden auf den markt gehen die auf diesen tag gewartet haben"),
    "es": ("el rápido zorro marrón salta sobre el perro perezoso y el "
           "tiempo hoy es bastante agradable porque vamos al mercado con "
           "nuestros amigos que esperaban este día desde hace mucho"),
    "it": ("la rapida volpe marrone salta sopra il cane pigro e il tempo "
           "oggi è piuttosto piacevole perché andiamo al mercato con i "
           "nostri amici che aspettavano questo giorno da molto tempo"),
    "pt": ("a rápida raposa marrom salta sobre o cão preguiçoso e o tempo "
           "hoje está bastante agradável porque vamos ao mercado com os "
           "nossos amigos que esperavam por este dia há muito tempo"),
    "nl": ("de snelle bruine vos springt over de luie hond en het weer is "
           "vandaag best aangenaam omdat we met onze vrienden naar de markt "
           "gaan die al lang op deze dag hebben gewacht"),
    "sv": ("den snabba bruna räven hoppar över den lata hunden och vädret "
           "idag är ganska trevligt eftersom vi ska till marknaden med våra "
           "vänner som har väntat på den här dagen länge"),
    "da": ("den hurtige brune ræv springer over den dovne hund og vejret i "
           "dag er ret behageligt fordi vi skal på markedet med vores "
           "venner som har ventet på denne dag længe"),
    "no": ("den raske brune reven hopper over den late hunden og været i "
           "dag er ganske hyggelig fordi vi skal til markedet med vennene "
           "våre som har ventet på denne dagen lenge"),
    "fi": ("nopea ruskea kettu hyppää laiskan koiran yli ja sää on tänään "
           "melko miellyttävä koska menemme torille ystäviemme kanssa "
           "jotka ovat odottaneet tätä päivää pitkään"),
    "pl": ("szybki brązowy lis przeskakuje nad leniwym psem a pogoda jest "
           "dzisiaj dość przyjemna ponieważ idziemy na targ z naszymi "
           "przyjaciółmi którzy długo czekali na ten dzień"),
    "cs": ("rychlá hnědá liška skáče přes líného psa a počasí je dnes "
           "docela příjemné protože jdeme na trh s našimi přáteli kteří na "
           "tento den dlouho čekali"),
    "sk": ("rýchla hnedá líška skáče cez lenivého psa a počasie je dnes "
           "celkom príjemné pretože ideme na trh s našimi priateľmi ktorí "
           "na tento deň dlho čakali"),
    "ro": ("vulpea maro rapidă sare peste câinele leneș iar vremea de "
           "astăzi este destul de plăcută pentru că mergem la piață cu "
           "prietenii noștri care au așteptat mult această zi"),
    "hu": ("a gyors barna róka átugrik a lusta kutya felett és az idő ma "
           "elég kellemes mert a piacra megyünk a barátainkkal akik régóta "
           "várták ezt a napot"),
    "tr": ("hızlı kahverengi tilki tembel köpeğin üzerinden atlar ve bugün "
           "hava oldukça güzel çünkü uzun zamandır bu günü bekleyen "
           "arkadaşlarımızla pazara gidiyoruz"),
    "vi": ("con cáo nâu nhanh nhẹn nhảy qua con chó lười biếng và thời "
           "tiết hôm nay khá dễ chịu vì chúng tôi sẽ đi chợ với những "
           "người bạn đã chờ đợi ngày này từ lâu"),
    "id": ("rubah coklat yang cepat melompati anjing yang malas dan cuaca "
           "hari ini cukup menyenangkan karena kami akan pergi ke pasar "
           "bersama teman teman kami yang sudah lama menunggu hari ini"),
    "ru": ("быстрая коричневая лиса прыгает через ленивую собаку и погода "
           "сегодня довольно приятная потому что мы идем на рынок с "
           "нашими друзьями которые давно ждали этот день"),
    "uk": ("швидка коричнева лисиця стрибає через ледачого пса і погода "
           "сьогодні досить приємна тому що ми йдемо на ринок з нашими "
           "друзями які давно чекали на цей день"),
    "bg": ("бързата кафява лисица прескача мързеливото куче и времето "
           "днес е доста приятно защото отиваме на пазара с нашите "
           "приятели които отдавна чакаха този ден"),
    "el": ("η γρήγορη καφέ αλεπού πηδάει πάνω από τον τεμπέλη σκύλο και ο "
           "καιρός σήμερα είναι αρκετά ευχάριστος επειδή πηγαίνουμε στην "
           "αγορά με τους φίλους μας που περίμεναν αυτή τη μέρα"),
    "ar": ("الثعلب البني السريع يقفز فوق الكلب الكسول والطقس اليوم لطيف "
           "إلى حد ما لأننا ذاهبون إلى السوق مع أصدقائنا الذين انتظروا "
           "هذا اليوم طويلا"),
    "fa": ("روباه قهوه ای سریع از روی سگ تنبل می پرد و هوای امروز نسبتا "
           "خوب است زیرا با دوستان خود که مدت ها منتظر این روز بودند به "
           "بازار می رویم"),
    "he": ("השועל החום המהיר קופץ מעל הכלב העצלן ומזג האוויר היום די נעים "
           "כי אנחנו הולכים לשוק עם החברים שלנו שחיכו ליום הזה הרבה זמן"),
    "hi": ("तेज भूरी लोमड़ी आलसी कुत्ते के ऊपर से कूदती है और आज का मौसम "
           "काफी सुहावना है क्योंकि हम अपने दोस्तों के साथ बाजार जा रहे "
           "हैं जो इस दिन का लंबे समय से इंतजार कर रहे थे"),
    "th": ("สุนัขจิ้งจอกสีน้ำตาลตัวเร็วกระโดดข้ามสุนัขขี้เกียจและอากาศวันนี้ค่อนข้างดีเพราะเราจะไป"
           "ตลาดกับเพื่อนของเราที่รอคอยวันนี้มานาน"),
    "zh": ("敏捷的棕色狐狸跳过懒狗今天的天气相当不错因为我们要和朋友一起去市场"
           "他们等这一天已经很久了"),
    "ja": ("すばやい茶色のキツネは怠け者の犬を飛び越えます今日の天気はかなり良い"
           "ので友達と一緒に市場に行きますこの日を長い間待っていました"),
    "ko": ("빠른 갈색 여우가 게으른 개를 뛰어넘고 오늘 날씨가 꽤 좋아서 "
           "오랫동안 이 날을 기다려온 친구들과 함께 시장에 갑니다"),
}

LANGUAGES = tuple(sorted(_SAMPLES))

_PROFILE_SIZE = 300

#: one-script languages resolvable from the dominant Unicode script alone
_SCRIPT_LANG = {
    "HANGUL": "ko", "GREEK": "el", "HEBREW": "he", "THAI": "th",
    "DEVANAGARI": "hi",
}


def _ngrams(text: str) -> Counter:
    """Character 1-3-gram counts over the normalized text (word-padded,
    textcat-style)."""
    counts: Counter = Counter()
    for word in text.lower().split():
        w = f" {word} "
        for n in (1, 2, 3):
            for i in range(len(w) - n + 1):
                counts[w[i:i + n]] += 1
    return counts


def _profile(text: str) -> dict[str, int]:
    """gram -> rank for the PROFILE_SIZE most frequent grams."""
    top = [g for g, _ in _ngrams(text).most_common(_PROFILE_SIZE)]
    return {g: r for r, g in enumerate(top)}


_PROFILES: dict[str, dict[str, int]] = {
    lang: _profile(text) for lang, text in _SAMPLES.items()
}


def _dominant_script(text: str) -> Optional[str]:
    """Coarse script vote via unicodedata names (first word of the name)."""
    votes: Counter = Counter()
    for ch in text[:200]:
        if ch.isspace() or not ch.isalpha():
            continue
        try:
            name = unicodedata.name(ch)
        except ValueError:
            continue
        votes[name.split()[0]] += 1
    if not votes:
        return None
    return votes.most_common(1)[0][0]


def language_scores(text: str) -> dict[str, float]:
    """lang -> similarity in (0, 1]; higher is better. Empty on no signal."""
    if not text or not any(ch.isalpha() for ch in text):
        return {}
    script = _dominant_script(text)
    if script == "CJK":
        # Han only -> Chinese; any kana -> Japanese
        has_kana = any("HIRAGANA" in unicodedata.name(c, "")
                       or "KATAKANA" in unicodedata.name(c, "")
                       for c in text[:200])
        return {"ja" if has_kana else "zh": 1.0}
    if script in ("HIRAGANA", "KATAKANA"):
        return {"ja": 1.0}
    if script in _SCRIPT_LANG:
        return {_SCRIPT_LANG[script]: 1.0}
    candidates = LANGUAGES
    if script == "CYRILLIC":
        candidates = ("ru", "uk", "bg")
    elif script == "ARABIC":
        candidates = ("ar", "fa")
    elif script == "LATIN":
        candidates = tuple(l for l in LANGUAGES if l not in
                           ("ru", "uk", "bg", "el", "ar", "fa", "he", "hi",
                            "th", "zh", "ja", "ko"))
    doc = [g for g, _ in _ngrams(text).most_common(_PROFILE_SIZE)]
    if not doc:
        return {}
    max_oop = _PROFILE_SIZE  # out-of-place penalty for a missing gram
    scores = {}
    for lang in candidates:
        prof = _PROFILES[lang]
        dist = sum(abs(prof.get(g, max_oop) - r) for r, g in enumerate(doc))
        worst = len(doc) * max_oop
        scores[lang] = 1.0 - dist / max(worst, 1)
    return scores


def detect_language_ngram(text: str) -> Optional[str]:
    """Best-scoring language code, or None when the text carries no
    alphabetic signal."""
    scores = language_scores(text)
    if not scores:
        return None
    return max(scores.items(), key=lambda kv: kv[1])[0]
