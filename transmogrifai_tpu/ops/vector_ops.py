"""Vector-surface ops: hashing TF, IDF, metadata-predicate column drops and
a standalone min-variance filter.

Parity: reference ``core/.../dsl/RichListFeature.scala:59-80`` (``tf`` /
``tfidf`` via Spark HashingTF + IDF), ``RichVectorFeature.scala:57-61``
(``idf``), ``core/.../stages/impl/feature/DropIndicesByTransformer.scala``
(drop vector columns by a metadata predicate) and
``core/.../stages/impl/preparators/MinVarianceFilter.scala`` (label-free
variance pruning).

TPU-first design notes: IDF document frequencies and column variances are
single jitted reductions over the device-resident vector block (the
reference runs a Spark ``treeAggregate`` per statistic); the fitted models
are DeviceTransformers so they fuse into their DAG layer's one XLA program.
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence, Union

import jax.numpy as jnp
import numpy as np

from transmogrifai_tpu import frame as fr
from transmogrifai_tpu.stages.base import (
    DeviceTransformer, Estimator, HostTransformer,
)
from transmogrifai_tpu.types import feature_types as ft
from transmogrifai_tpu.vector_metadata import (
    VectorColumnMetadata, VectorMetadata, parent_of,
)
from transmogrifai_tpu.ops.vectorizers.hashing import hash_token

__all__ = [
    "OpHashingTF", "OpIDF", "IDFModel", "DropIndicesByTransformer",
    "MinVarianceFilter", "MinVarianceFilterModel",
]


class OpHashingTF(HostTransformer):
    """TextList -> OPVector of hashed term frequencies (reference
    ``OpHashingTF.scala`` wrapping Spark HashingTF; RichListFeature ``tf``).

    Tokens are hashed (shared CRC-32 token hash with the text hashing
    vectorizer) into ``num_features`` bins; ``binary_freq`` records presence
    instead of counts.
    """

    in_types = (ft.TextList,)
    out_type = ft.OPVector

    def __init__(self, num_features: int = 512, binary_freq: bool = False,
                 uid: Optional[str] = None):
        self.num_features = int(num_features)
        self.binary_freq = bool(binary_freq)
        super().__init__(uid=uid)

    def transform_row(self, value):
        out = np.zeros(self.num_features, dtype=np.float32)
        for tok in (value or ()):
            out[hash_token(str(tok), self.num_features)] += 1.0
        if self.binary_freq:
            out = (out > 0).astype(np.float32)
        return out

    def host_apply(self, *cols: fr.HostColumn) -> fr.HostColumn:
        col = cols[0]
        vals = (np.stack([self.transform_row(v) for v in col.values])
                if len(col) else np.zeros((0, self.num_features), np.float32))
        return fr.HostColumn(ft.OPVector, vals, meta=self._meta())

    def _meta(self) -> VectorMetadata:
        f = self.input_features[0]
        cols = tuple(
            VectorColumnMetadata(*parent_of(f), grouping=f.name,
                                 descriptor_value=f"hash_{i}")
            for i in range(self.num_features))
        return VectorMetadata(self.get_output().name, cols).reindexed(0)


class OpIDF(Estimator):
    """OPVector -> OPVector inverse-document-frequency scaling (reference
    RichVectorFeature ``idf``; Spark ``IDF`` semantics).

    idf(t) = log((m + 1) / (df(t) + 1)) with df(t) = #docs where column t is
    nonzero; terms appearing in fewer than ``min_doc_freq`` documents get
    weight 0. The df pass is one jitted device reduction.
    """

    in_types = (ft.OPVector,)
    out_type = ft.OPVector

    def __init__(self, min_doc_freq: int = 0, uid: Optional[str] = None):
        self.min_doc_freq = int(min_doc_freq)
        super().__init__(uid=uid)

    def fit_model(self, data) -> "IDFModel":
        col = data.device_col(self.input_names[0])
        x = col.values
        # weight by the row validity mask: device blocks may carry mesh
        # padding rows which must contribute monoid identity
        mask = data.row_mask()
        m = jnp.sum(mask)
        df = jnp.sum((x != 0.0) * mask[:, None], axis=0, dtype=jnp.float32)
        idf = jnp.log((m + 1.0) / (df + 1.0))
        idf = jnp.where(df >= self.min_doc_freq, idf, 0.0)
        return IDFModel(idf=np.asarray(idf, dtype=np.float32))


class IDFModel(DeviceTransformer):
    in_types = (ft.OPVector,)
    out_type = ft.OPVector

    def __init__(self, idf: Optional[Sequence[float]] = None,
                 uid: Optional[str] = None):
        self.idf = None if idf is None else np.asarray(idf, dtype=np.float32)
        super().__init__(uid=uid)

    def device_params(self):
        return jnp.asarray(self.idf)

    def device_apply(self, params, col: fr.VectorColumn) -> fr.VectorColumn:
        return fr.VectorColumn(col.values * params[None, :], col.metadata)

    def transform_row(self, value):
        return np.asarray(value, dtype=np.float32) * self.idf

    def config(self) -> dict:
        return {}

    def fitted_state(self) -> dict:
        return {"idf": self.idf}

    def set_fitted_state(self, state: dict) -> None:
        self.idf = np.asarray(state["idf"], dtype=np.float32)


#: name -> predicate over VectorColumnMetadata, the serializable registry
#: for DropIndicesByTransformer (the reference serializes the predicate
#: class name; we register named predicates the same way)
DROP_PREDICATES: dict[str, Callable[[VectorColumnMetadata], bool]] = {
    "null_indicator": lambda c: c.is_null_indicator,
    "other_indicator": lambda c: c.is_other_indicator,
}


def register_drop_predicate(
        name: str, fn: Callable[[VectorColumnMetadata], bool]) -> None:
    DROP_PREDICATES[name] = fn


class DropIndicesByTransformer(DeviceTransformer):
    """OPVector -> OPVector dropping every column whose metadata matches the
    predicate (reference ``DropIndicesByTransformer.scala`` /
    RichVectorFeature ``dropIndicesBy``).

    The predicate is either a registered name (serializable — see
    ``DROP_PREDICATES``) or a callable over ``VectorColumnMetadata`` (not
    serializable, mirroring the reference's requirement that the predicate
    be a stable class for model save).

    Keep-indices resolve from the input metadata at trace time, so the
    gather has a static shape and fuses into the layer program; the resolved
    set is remembered so the metadata-less local row path (and the
    serialized model) drop exactly the same columns the columnar pass did —
    in the reference the metadata rides on the DataFrame schema, here it
    rides on the fitted stage.
    """

    in_types = (ft.OPVector,)
    out_type = ft.OPVector

    def __init__(self, match_fn: Union[str, Callable] = "null_indicator",
                 keep_indices: Optional[Sequence[int]] = None,
                 uid: Optional[str] = None):
        self.match_fn = match_fn
        self.keep_indices = (None if keep_indices is None
                             else [int(i) for i in keep_indices])
        super().__init__(uid=uid)

    def _predicate(self) -> Callable[[VectorColumnMetadata], bool]:
        if callable(self.match_fn):
            return self.match_fn
        try:
            return DROP_PREDICATES[self.match_fn]
        except KeyError:
            raise KeyError(
                f"unknown drop predicate {self.match_fn!r}; register it via "
                "register_drop_predicate") from None

    def _keep(self, meta: Optional[VectorMetadata], width: int) -> list[int]:
        if meta is None or meta.size != width:
            if self.keep_indices is None:
                raise RuntimeError(
                    "DropIndicesByTransformer has no vector metadata and no "
                    "resolved keep_indices; run the columnar pass (or pass "
                    "keep_indices) before row-level transform — silently "
                    "keeping every column would turn the drop into a no-op")
            return self.keep_indices
        p = self._predicate()
        return [i for i, c in enumerate(meta.columns) if not p(c)]

    def device_apply(self, params, col: fr.VectorColumn) -> fr.VectorColumn:
        keep = self._keep(col.metadata, int(col.values.shape[1]))
        self.keep_indices = keep
        meta = (col.metadata.select(keep)
                if col.metadata is not None
                and col.metadata.size == int(col.values.shape[1]) else None)
        return fr.VectorColumn(
            jnp.take(col.values, jnp.asarray(keep, jnp.int32), axis=1), meta)

    def transform_row(self, value):
        vec = np.asarray(value, dtype=np.float32)
        keep = self._keep(None, vec.shape[0])
        return vec[np.asarray(keep, dtype=np.int64)]

    def config(self) -> dict:
        if callable(self.match_fn):
            raise NotImplementedError(
                "DropIndicesByTransformer with a raw callable predicate is "
                "not serializable; register it by name")
        return {"match_fn": self.match_fn,
                "keep_indices": self.keep_indices}


class MinVarianceFilter(Estimator):
    """OPVector -> OPVector dropping columns with variance below the
    threshold — the SanityChecker's minVariance rule standalone and
    label-free (reference ``MinVarianceFilter.scala:159``).

    One jitted moment pass over the device block.
    """

    in_types = (ft.OPVector,)
    out_type = ft.OPVector

    def __init__(self, min_variance: float = 1e-5,
                 uid: Optional[str] = None):
        self.min_variance = float(min_variance)
        super().__init__(uid=uid)

    def fit_model(self, data) -> "MinVarianceFilterModel":
        col = data.device_col(self.input_names[0])
        x = col.values
        mask = data.row_mask()
        n = jnp.maximum(jnp.sum(mask), 1.0)
        mean = jnp.sum(x * mask[:, None], axis=0) / n
        # centered second pass: E[x^2]-mean^2 catastrophically cancels in
        # float32 for large-mean columns (a constant ~5e4 column would read
        # variance ~3e3); masked so mesh-padding rows contribute identity.
        # Sample variance (1/(n-1)) and a strict > keep match the reference
        # (Spark Summarizer variance; drop when variance <= minVariance).
        d = (x - mean[None, :]) * mask[:, None]
        var = jnp.sum(d * d, axis=0) / jnp.maximum(n - 1.0, 1.0)
        keep = [int(i) for i in
                np.flatnonzero(np.asarray(var) > self.min_variance)]
        meta = (col.metadata.select(keep)
                if col.metadata is not None
                and col.metadata.size == int(x.shape[1]) else None)
        return MinVarianceFilterModel(keep_indices=keep, out_meta=meta)


class MinVarianceFilterModel(DeviceTransformer):
    in_types = (ft.OPVector,)
    out_type = ft.OPVector

    def __init__(self, keep_indices: Sequence[int] = (),
                 out_meta: Optional[VectorMetadata] = None,
                 uid: Optional[str] = None):
        self.keep_indices = [int(i) for i in keep_indices]
        self.out_meta = out_meta
        super().__init__(uid=uid)

    def device_params(self):
        return jnp.asarray(self.keep_indices, jnp.int32)

    def device_apply(self, params, col: fr.VectorColumn) -> fr.VectorColumn:
        meta = self.out_meta
        if meta is None and col.metadata is not None \
                and col.metadata.size == int(col.values.shape[1]):
            meta = col.metadata.select(self.keep_indices)
        return fr.VectorColumn(jnp.take(col.values, params, axis=1), meta)

    def transform_row(self, value):
        vec = np.asarray(value, dtype=np.float32)
        return vec[np.asarray(self.keep_indices, dtype=np.int64)]

    def config(self) -> dict:
        return {
            "keep_indices": self.keep_indices,
            "out_meta": self.out_meta.to_json() if self.out_meta else None,
        }

    @classmethod
    def from_config(cls, config, uid=None):
        meta = (VectorMetadata.from_json(config["out_meta"])
                if config.get("out_meta") else None)
        return cls(keep_indices=config.get("keep_indices", ()),
                   out_meta=meta, uid=uid)
