"""Type-specific parsers: email, URL, phone, base64 MIME detection.

Parity: reference ``core/.../stages/impl/feature/{ValidEmailTransformer,
EmailToPickListMapTransformer, UrlMapToPickListMapTransformer,
PhoneNumberParser, MimeTypeDetector}.scala``. The reference leans on Google
libphonenumber and Apache Tika; here validity is rule-based (E.164 length +
region prefix table; magic-byte MIME table) — same stage surface, no JVM
deps.
"""

from __future__ import annotations

import base64 as _b64
import re
from typing import Optional

from transmogrifai_tpu.stages.base import HostTransformer
from transmogrifai_tpu.types import feature_types as ft

__all__ = [
    "ValidEmailTransformer", "EmailToPickList", "UrlToPickList",
    "ValidUrlTransformer", "PhoneNumberParser", "MimeTypeDetector",
    "ParsePhoneNumber", "ParsePhoneDefaultCountry", "IsValidPhoneNumber",
    "IsValidPhoneMapDefaultCountry", "PHONE_REGIONS", "parse_phone",
    "detect_mime", "EmailPrefixTransformer", "UrlProtocolTransformer",
]

_EMAIL_RE = re.compile(
    r"^[A-Za-z0-9.!#$%&'*+/=?^_`{|}~-]+@[A-Za-z0-9-]+(\.[A-Za-z0-9-]+)+$")
_URL_RE = re.compile(
    r"^(https?|ftp)://[^\s/$.?#].[^\s]*$", re.IGNORECASE)

#: per-region phone metadata: ISO alpha-2 -> (calling code, min national
#: digits, max national digits, trunk prefix stripped in national format).
#: The libphonenumber-lite table behind validate/parse (reference
#: PhoneNumberParser.scala defers to Google's metadata; this covers the
#: same contract — region-dependent validity — for ~40 regions).
PHONE_REGIONS: dict[str, tuple[str, int, int, str]] = {
    "US": ("1", 10, 10, ""),   "CA": ("1", 10, 10, ""),
    "GB": ("44", 9, 10, "0"),  "DE": ("49", 6, 11, "0"),
    "FR": ("33", 9, 9, "0"),   "ES": ("34", 9, 9, ""),
    "IT": ("39", 8, 11, ""),   "PT": ("351", 9, 9, ""),
    "NL": ("31", 9, 9, "0"),   "BE": ("32", 8, 9, "0"),
    "CH": ("41", 9, 9, "0"),   "AT": ("43", 8, 12, "0"),
    "SE": ("46", 7, 10, "0"),  "NO": ("47", 8, 8, ""),
    "DK": ("45", 8, 8, ""),    "FI": ("358", 7, 11, "0"),
    "PL": ("48", 9, 9, ""),    "CZ": ("420", 9, 9, ""),
    "RU": ("7", 10, 10, "8"),  "UA": ("380", 9, 9, "0"),
    "TR": ("90", 10, 10, "0"), "GR": ("30", 10, 10, ""),
    "IE": ("353", 7, 10, "0"), "JP": ("81", 9, 10, "0"),
    "CN": ("86", 11, 11, "0"), "KR": ("82", 8, 11, "0"),
    "IN": ("91", 10, 10, "0"), "AU": ("61", 9, 9, "0"),
    "NZ": ("64", 8, 10, "0"),  "BR": ("55", 10, 11, ""),
    "MX": ("52", 10, 10, ""),  "AR": ("54", 10, 10, "0"),
    "ZA": ("27", 9, 9, "0"),   "NG": ("234", 10, 10, "0"),
    "EG": ("20", 10, 10, "0"), "SA": ("966", 9, 9, "0"),
    "AE": ("971", 9, 9, "0"),  "IL": ("972", 8, 9, "0"),
    "SG": ("65", 8, 8, ""),    "HK": ("852", 8, 8, ""),
    "TH": ("66", 9, 9, "0"),   "ID": ("62", 9, 12, "0"),
    "PH": ("63", 10, 10, "0"), "VN": ("84", 9, 10, "0"),
}

#: country display name -> ISO region (reference DefaultCountryCodes)
COUNTRY_NAMES: dict[str, str] = {
    "UNITED STATES": "US", "UNITED STATES OF AMERICA": "US", "CANADA": "CA",
    "UNITED KINGDOM": "GB", "GREAT BRITAIN": "GB", "GERMANY": "DE",
    "FRANCE": "FR", "SPAIN": "ES", "ITALY": "IT", "PORTUGAL": "PT",
    "NETHERLANDS": "NL", "BELGIUM": "BE", "SWITZERLAND": "CH",
    "AUSTRIA": "AT", "SWEDEN": "SE", "NORWAY": "NO", "DENMARK": "DK",
    "FINLAND": "FI", "POLAND": "PL", "CZECHIA": "CZ", "RUSSIA": "RU",
    "UKRAINE": "UA", "TURKEY": "TR", "GREECE": "GR", "IRELAND": "IE",
    "JAPAN": "JP", "CHINA": "CN", "SOUTH KOREA": "KR", "KOREA": "KR",
    "INDIA": "IN", "AUSTRALIA": "AU", "NEW ZEALAND": "NZ", "BRAZIL": "BR",
    "MEXICO": "MX", "ARGENTINA": "AR", "SOUTH AFRICA": "ZA",
    "NIGERIA": "NG", "EGYPT": "EG", "SAUDI ARABIA": "SA",
    "UNITED ARAB EMIRATES": "AE", "ISRAEL": "IL", "SINGAPORE": "SG",
    "HONG KONG": "HK", "THAILAND": "TH", "INDONESIA": "ID",
    "PHILIPPINES": "PH", "VIETNAM": "VN",
}

#: calling code -> a representative region, longest codes first (for "+"
#: international parses)
_BY_CALLING_CODE = sorted(
    {meta[0]: iso for iso, meta in sorted(PHONE_REGIONS.items(),
                                          reverse=True)}.items(),
    key=lambda kv: -len(kv[0]))


def resolve_region(region: Optional[str],
                   default_region: str = "US") -> str:
    """ISO code, country name, or calling code -> ISO region (reference
    validCountryCode: tries codes then names, falls back to default)."""
    if not region:
        return default_region
    r = str(region).strip().upper()
    if r in PHONE_REGIONS:
        return r
    if r in COUNTRY_NAMES:
        return COUNTRY_NAMES[r]
    digits = re.sub(r"[^\d]", "", r)
    if digits:
        for code, iso in _BY_CALLING_CODE:
            if digits == code:
                return iso
    return default_region


def clean_number(s: str) -> str:
    """Trim + drop everything but digits and a leading '+' (reference
    cleanNumber)."""
    s = s.strip()
    plus = s.startswith("+")
    digits = re.sub(r"[^\d]", "", s)
    return ("+" + digits) if plus else digits


def parse_phone(s: str, region: str = "US",
                strict: bool = False) -> Optional[str]:
    """Normalize to E.164 (+<cc><national>); None when invalid.

    Semantics follow the reference's libphonenumber usage
    (PhoneNumberParser.scala:258-276): numbers under 2 digits are invalid;
    a leading '+' forces international parsing; otherwise the region's
    metadata applies (trunk prefix stripped, an embedded country code
    accepted); non-strict mode truncates too-long numbers before
    validating (truncateTooLongNumber)."""
    cleaned = clean_number(s)
    plus = cleaned.startswith("+")
    digits = cleaned[1:] if plus else cleaned
    if len(digits) < 2:
        return None
    if plus:
        for code, iso in _BY_CALLING_CODE:
            if digits.startswith(code):
                _, lo, hi, _ = PHONE_REGIONS[iso]
                national = digits[len(code):]
                if not strict and len(national) > hi:
                    national = national[:hi]
                if lo <= len(national) <= hi:
                    return f"+{code}{national}"
                return None
        return None
    iso = resolve_region(region)
    code, lo, hi, trunk = PHONE_REGIONS[iso]
    national = digits
    # national trunk prefix ("0" in most of the world, "8" in RU)
    if trunk and national.startswith(trunk) \
            and lo <= len(national) - len(trunk) <= hi:
        national = national[len(trunk):]
    # an embedded country code ("49 30 1234567" without the +)
    elif national.startswith(code) and \
            lo <= len(national) - len(code) <= hi:
        national = national[len(code):]
    if not strict and len(national) > hi:
        national = national[:hi]
    if lo <= len(national) <= hi:
        return f"+{code}{national}"
    return None


# -- MIME ------------------------------------------------------------------

_MIME_MAGIC = [
    (b"\x89PNG\r\n\x1a\n", "image/png"),
    (b"\xff\xd8\xff", "image/jpeg"),
    (b"GIF87a", "image/gif"),
    (b"GIF89a", "image/gif"),
    (b"%PDF-", "application/pdf"),
    (b"\x1f\x8b", "application/gzip"),
    (b"BM", "image/bmp"),
    (b"<?xml", "application/xml"),
    (b"{", "application/json"),
    (b"OggS", "audio/ogg"),
    (b"\x7fELF", "application/x-executable"),
    (b"\xd0\xcf\x11\xe0\xa1\xb1\x1a\xe1", "application/x-ole-storage"),
    (b"ID3", "audio/mpeg"),
    (b"\xff\xfb", "audio/mpeg"),
    (b"fLaC", "audio/flac"),
    (b"7z\xbc\xaf\x27\x1c", "application/x-7z-compressed"),
    (b"Rar!", "application/x-rar-compressed"),
    (b"\x00\x00\x01\x00", "image/x-icon"),
]


def _zip_mime(data: bytes) -> str:
    """Look inside ZIP containers the way Tika does: OOXML types declare
    themselves by their internal directory layout."""
    import io
    import zipfile
    try:
        with zipfile.ZipFile(io.BytesIO(data)) as z:
            names = set(z.namelist())
    except Exception:  # failure-ok: unreadable zip still reports the generic mime
        return "application/zip"
    if any(n.startswith("word/") for n in names):
        return ("application/vnd.openxmlformats-officedocument"
                ".wordprocessingml.document")
    if any(n.startswith("xl/") for n in names):
        return ("application/vnd.openxmlformats-officedocument"
                ".spreadsheetml.sheet")
    if any(n.startswith("ppt/") for n in names):
        return ("application/vnd.openxmlformats-officedocument"
                ".presentationml.presentation")
    if "META-INF/MANIFEST.MF" in names:
        return "application/java-archive"
    return "application/zip"


def is_valid_email(s: str) -> bool:
    return bool(_EMAIL_RE.match(s)) and len(s) <= 254


def is_valid_url(s: str) -> bool:
    return bool(_URL_RE.match(s))


def detect_mime(data: bytes) -> Optional[str]:
    if data.startswith(b"PK\x03\x04"):
        return _zip_mime(data)
    if data.startswith(b"RIFF"):
        kind = data[8:12] if len(data) >= 12 else b""
        return {b"WAVE": "audio/wav", b"WEBP": "image/webp",
                b"AVI ": "video/x-msvideo"}.get(kind, "audio/wav")
    if len(data) >= 12 and data[4:8] == b"ftyp":
        return "video/mp4"
    for magic, mime in _MIME_MAGIC:
        if data.startswith(magic):
            return mime
    try:
        data.decode("utf-8")
        return "text/plain"
    except UnicodeDecodeError:
        return "application/octet-stream"


class ValidEmailTransformer(HostTransformer):
    in_types = (ft.Email,)
    out_type = ft.Binary

    def __init__(self, uid=None):
        super().__init__(uid=uid)

    def transform_row(self, value):
        return None if value is None else is_valid_email(value)


class EmailToPickList(HostTransformer):
    """Email -> domain PickList (invalid -> None)."""

    in_types = (ft.Email,)
    out_type = ft.PickList

    def __init__(self, uid=None):
        super().__init__(uid=uid)

    def transform_row(self, value):
        if value is None or not is_valid_email(value):
            return None
        return value.rsplit("@", 1)[1].lower()


class EmailPrefixTransformer(HostTransformer):
    """Email -> local-part Text (reference RichTextFeature ``toEmailPrefix``
    via EmailPrefixToText); invalid -> None."""

    in_types = (ft.Email,)
    out_type = ft.Text

    def __init__(self, uid=None):
        super().__init__(uid=uid)

    def transform_row(self, value):
        if value is None or not is_valid_email(value):
            return None
        return value.rsplit("@", 1)[0]


class ValidUrlTransformer(HostTransformer):
    in_types = (ft.URL,)
    out_type = ft.Binary

    def __init__(self, uid=None):
        super().__init__(uid=uid)

    def transform_row(self, value):
        return None if value is None else is_valid_url(value)


class UrlToPickList(HostTransformer):
    """URL -> hostname PickList (invalid -> None)."""

    in_types = (ft.URL,)
    out_type = ft.PickList

    def __init__(self, uid=None):
        super().__init__(uid=uid)

    def transform_row(self, value):
        if value is None or not is_valid_url(value):
            return None
        host = re.sub(r"^[a-z+]+://", "", value.lower()).split("/")[0]
        return host.split(":")[0] or None


class UrlProtocolTransformer(HostTransformer):
    """URL -> protocol Text, e.g. 'http' (reference RichTextFeature
    ``toProtocol`` via URLProtocolToText); invalid -> None."""

    in_types = (ft.URL,)
    out_type = ft.Text

    def __init__(self, uid=None):
        super().__init__(uid=uid)

    def transform_row(self, value):
        if value is None or not is_valid_url(value):
            return None
        return value.split("://", 1)[0].lower()


class _PhoneBase(HostTransformer):
    def __init__(self, default_region: str = "US", strict: bool = False,
                 uid=None):
        self.default_region = resolve_region(default_region)
        self.strict = bool(strict)
        super().__init__(uid=uid)


class ParsePhoneDefaultCountry(_PhoneBase):
    """Phone -> normalized E.164 Phone under the default region (reference
    ParsePhoneDefaultCountry); invalid -> None."""

    in_types = (ft.Phone,)
    out_type = ft.Phone

    def transform_row(self, value):
        if value is None:
            return None
        return parse_phone(value, self.default_region, self.strict)


class ParsePhoneNumber(_PhoneBase):
    """(Phone, Text region) -> normalized E.164 Phone; the region input may
    be an ISO code, country name, or calling code (reference
    ParsePhoneNumber + validCountryCode)."""

    in_types = (ft.Phone, ft.Text)
    out_type = ft.Phone

    def transform_row(self, value, region):
        if value is None:
            return None
        return parse_phone(value,
                           resolve_region(region, self.default_region),
                           self.strict)


class PhoneNumberParser(_PhoneBase):
    """Phone -> Binary validity under the default region (reference
    IsValidPhoneDefaultCountry; numbers under 2 digits invalid)."""

    in_types = (ft.Phone,)
    out_type = ft.Binary

    def transform_row(self, value):
        if value is None:
            return None
        return parse_phone(value, self.default_region, self.strict) \
            is not None


class IsValidPhoneNumber(_PhoneBase):
    """(Phone, Text region) -> Binary validity (reference
    IsValidPhoneNumber)."""

    in_types = (ft.Phone, ft.Text)
    out_type = ft.Binary

    def transform_row(self, value, region):
        if value is None:
            return None
        return parse_phone(value,
                           resolve_region(region, self.default_region),
                           self.strict) is not None


class IsValidPhoneMapDefaultCountry(_PhoneBase):
    """PhoneMap -> BinaryMap of per-key validity (reference
    IsValidPhoneMapDefaultCountry; missing values drop from the map)."""

    in_types = (ft.PhoneMap,)
    out_type = ft.BinaryMap

    def transform_row(self, value):
        if not value:
            return {}
        return {k: parse_phone(v, self.default_region, self.strict)
                is not None
                for k, v in value.items() if v is not None}


class MimeTypeDetector(HostTransformer):
    """Base64 -> PickList MIME type via magic bytes."""

    in_types = (ft.Base64,)
    out_type = ft.PickList

    def __init__(self, uid=None):
        super().__init__(uid=uid)

    def transform_row(self, value):
        if value is None:
            return None
        try:
            data = _b64.b64decode(value, validate=False)
        except Exception:  # failure-ok: invalid base64 value parses as missing
            return None
        if not data:
            return None
        return detect_mime(data)
