"""Type-specific parsers: email, URL, phone, base64 MIME detection.

Parity: reference ``core/.../stages/impl/feature/{ValidEmailTransformer,
EmailToPickListMapTransformer, UrlMapToPickListMapTransformer,
PhoneNumberParser, MimeTypeDetector}.scala``. The reference leans on Google
libphonenumber and Apache Tika; here validity is rule-based (E.164 length +
region prefix table; magic-byte MIME table) — same stage surface, no JVM
deps.
"""

from __future__ import annotations

import base64 as _b64
import re
from typing import Optional

from transmogrifai_tpu.stages.base import HostTransformer
from transmogrifai_tpu.types import feature_types as ft

__all__ = [
    "ValidEmailTransformer", "EmailToPickList", "UrlToPickList",
    "ValidUrlTransformer", "PhoneNumberParser", "MimeTypeDetector",
]

_EMAIL_RE = re.compile(
    r"^[A-Za-z0-9.!#$%&'*+/=?^_`{|}~-]+@[A-Za-z0-9-]+(\.[A-Za-z0-9-]+)+$")
_URL_RE = re.compile(
    r"^(https?|ftp)://[^\s/$.?#].[^\s]*$", re.IGNORECASE)

#: country calling code -> national number length range (subset)
_PHONE_REGIONS = {
    "1": (10, 10),    # US/CA
    "44": (9, 10),    # UK
    "49": (7, 11),    # DE
    "33": (9, 9),     # FR
    "81": (9, 10),    # JP
    "86": (11, 11),   # CN
    "91": (10, 10),   # IN
    "61": (9, 9),     # AU
    "55": (10, 11),   # BR
}

_MIME_MAGIC = [
    (b"\x89PNG\r\n\x1a\n", "image/png"),
    (b"\xff\xd8\xff", "image/jpeg"),
    (b"GIF87a", "image/gif"),
    (b"GIF89a", "image/gif"),
    (b"%PDF-", "application/pdf"),
    (b"PK\x03\x04", "application/zip"),
    (b"\x1f\x8b", "application/gzip"),
    (b"BM", "image/bmp"),
    (b"<?xml", "application/xml"),
    (b"{", "application/json"),
    (b"RIFF", "audio/wav"),
    (b"OggS", "audio/ogg"),
    (b"\x7fELF", "application/x-executable"),
]


def is_valid_email(s: str) -> bool:
    return bool(_EMAIL_RE.match(s)) and len(s) <= 254


def is_valid_url(s: str) -> bool:
    return bool(_URL_RE.match(s))


def parse_phone(s: str, default_region_code: str = "1"
                ) -> Optional[str]:
    """Normalize to E.164-ish digits; None when invalid."""
    s = s.strip()
    plus = s.startswith("+")
    digits = re.sub(r"[^\d]", "", s)
    if not digits:
        return None
    if plus:
        for code, (lo, hi) in _PHONE_REGIONS.items():
            if digits.startswith(code):
                national = digits[len(code):]
                if lo <= len(national) <= hi:
                    return "+" + digits
        return None
    lo, hi = _PHONE_REGIONS.get(default_region_code, (7, 15))
    if lo <= len(digits) <= hi:
        return f"+{default_region_code}{digits}"
    return None


def detect_mime(data: bytes) -> Optional[str]:
    for magic, mime in _MIME_MAGIC:
        if data.startswith(magic):
            return mime
    try:
        data.decode("utf-8")
        return "text/plain"
    except UnicodeDecodeError:
        return "application/octet-stream"


class ValidEmailTransformer(HostTransformer):
    in_types = (ft.Email,)
    out_type = ft.Binary

    def __init__(self, uid=None):
        super().__init__(uid=uid)

    def transform_row(self, value):
        return None if value is None else is_valid_email(value)


class EmailToPickList(HostTransformer):
    """Email -> domain PickList (invalid -> None)."""

    in_types = (ft.Email,)
    out_type = ft.PickList

    def __init__(self, uid=None):
        super().__init__(uid=uid)

    def transform_row(self, value):
        if value is None or not is_valid_email(value):
            return None
        return value.rsplit("@", 1)[1].lower()


class ValidUrlTransformer(HostTransformer):
    in_types = (ft.URL,)
    out_type = ft.Binary

    def __init__(self, uid=None):
        super().__init__(uid=uid)

    def transform_row(self, value):
        return None if value is None else is_valid_url(value)


class UrlToPickList(HostTransformer):
    """URL -> hostname PickList (invalid -> None)."""

    in_types = (ft.URL,)
    out_type = ft.PickList

    def __init__(self, uid=None):
        super().__init__(uid=uid)

    def transform_row(self, value):
        if value is None or not is_valid_url(value):
            return None
        host = re.sub(r"^[a-z+]+://", "", value.lower()).split("/")[0]
        return host.split(":")[0] or None


class PhoneNumberParser(HostTransformer):
    """Phone -> Binary validity (reference PhoneNumberParser.isValid path)."""

    in_types = (ft.Phone,)
    out_type = ft.Binary

    def __init__(self, default_region_code: str = "1", uid=None):
        self.default_region_code = default_region_code
        super().__init__(uid=uid)

    def transform_row(self, value):
        if value is None:
            return None
        return parse_phone(value, self.default_region_code) is not None


class MimeTypeDetector(HostTransformer):
    """Base64 -> PickList MIME type via magic bytes."""

    in_types = (ft.Base64,)
    out_type = ft.PickList

    def __init__(self, uid=None):
        super().__init__(uid=uid)

    def transform_row(self, value):
        if value is None:
            return None
        try:
            data = _b64.b64decode(value, validate=False)
        except Exception:
            return None
        if not data:
            return None
        return detect_mime(data)
