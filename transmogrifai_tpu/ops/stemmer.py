"""Porter stemmer — the analyzer-chain depth piece of the text pipeline.

Parity: reference ``TextTokenizer.scala`` routes English through Lucene's
``EnglishAnalyzer`` whose final stage is a PorterStemFilter; this is the
classic Porter (1980) algorithm implemented from its published definition
(steps 1a-5b over the m-measure of the C/V form), so ``running`` ->
``run``, ``relational`` -> ``relat``, ``adjustable`` -> ``adjust`` match
Lucene's output on the standard vocabulary.

Host-side by design (string work never belongs on the device path); one
pure function, no state.
"""

from __future__ import annotations

__all__ = ["porter_stem"]

_VOWELS = frozenset("aeiou")


def _is_cons(word: str, i: int) -> bool:
    ch = word[i]
    if ch in _VOWELS:
        return False
    if ch == "y":
        return i == 0 or not _is_cons(word, i - 1)
    return True


def _measure(stem: str) -> int:
    """m in the [C](VC){m}[V] decomposition."""
    m = 0
    prev_v = False
    for i in range(len(stem)):
        v = not _is_cons(stem, i)
        if prev_v and not v:
            m += 1
        prev_v = v
    return m


def _has_vowel(stem: str) -> bool:
    return any(not _is_cons(stem, i) for i in range(len(stem)))


def _ends_double_cons(word: str) -> bool:
    return (len(word) >= 2 and word[-1] == word[-2]
            and _is_cons(word, len(word) - 1))


def _cvc(word: str) -> bool:
    """*o: stem ends cvc where the final c is not w, x or y."""
    if len(word) < 3:
        return False
    return (_is_cons(word, len(word) - 3)
            and not _is_cons(word, len(word) - 2)
            and _is_cons(word, len(word) - 1)
            and word[-1] not in "wxy")


def _replace(word: str, suffix: str, repl: str, min_m: int) -> str | None:
    if not word.endswith(suffix):
        return None
    stem = word[: len(word) - len(suffix)]
    if _measure(stem) > min_m - 1:
        return stem + repl
    return word  # suffix matched but condition failed: stop this step


_STEP2 = [("ational", "ate"), ("tional", "tion"), ("enci", "ence"),
          ("anci", "ance"), ("izer", "ize"), ("abli", "able"),
          ("alli", "al"), ("entli", "ent"), ("eli", "e"), ("ousli", "ous"),
          ("ization", "ize"), ("ation", "ate"), ("ator", "ate"),
          ("alism", "al"), ("iveness", "ive"), ("fulness", "ful"),
          ("ousness", "ous"), ("aliti", "al"), ("iviti", "ive"),
          ("biliti", "ble")]

_STEP3 = [("icate", "ic"), ("ative", ""), ("alize", "al"), ("iciti", "ic"),
          ("ical", "ic"), ("ful", ""), ("ness", "")]

_STEP4 = ["al", "ance", "ence", "er", "ic", "able", "ible", "ant", "ement",
          "ment", "ent", "ou", "ism", "ate", "iti", "ous", "ive", "ize"]


def porter_stem(word: str) -> str:
    w = word.lower()
    if len(w) <= 2:
        return w

    # step 1a: plurals
    if w.endswith("sses"):
        w = w[:-2]
    elif w.endswith("ies"):
        w = w[:-2]
    elif not w.endswith("ss") and w.endswith("s"):
        w = w[:-1]

    # step 1b: -ed / -ing
    if w.endswith("eed"):
        if _measure(w[:-3]) > 0:
            w = w[:-1]
    else:
        flag = False
        if w.endswith("ed") and _has_vowel(w[:-2]):
            w, flag = w[:-2], True
        elif w.endswith("ing") and _has_vowel(w[:-3]):
            w, flag = w[:-3], True
        if flag:
            if w.endswith(("at", "bl", "iz")):
                w += "e"
            elif _ends_double_cons(w) and not w.endswith(("l", "s", "z")):
                w = w[:-1]
            elif _measure(w) == 1 and _cvc(w):
                w += "e"

    # step 1c: y -> i after a vowel
    if w.endswith("y") and _has_vowel(w[:-1]):
        w = w[:-1] + "i"

    # step 2
    for suf, repl in _STEP2:
        if w.endswith(suf):
            stem = w[: len(w) - len(suf)]
            if _measure(stem) > 0:
                w = stem + repl
            break

    # step 3
    for suf, repl in _STEP3:
        if w.endswith(suf):
            stem = w[: len(w) - len(suf)]
            if _measure(stem) > 0:
                w = stem + repl
            break

    # step 4: drop when m > 1 (the -ion case additionally needs s/t)
    for suf in _STEP4:
        if w.endswith(suf):
            stem = w[: len(w) - len(suf)]
            if _measure(stem) > 1:
                w = stem
            break
    else:
        if w.endswith("ion") and len(w) > 3 and w[-4] in "st" \
                and _measure(w[:-3]) > 1:
            w = w[:-3]

    # step 5a: final -e
    if w.endswith("e"):
        stem = w[:-1]
        m = _measure(stem)
        if m > 1 or (m == 1 and not _cvc(stem)):
            w = stem
    # step 5b: -ll -> -l when m > 1
    if w.endswith("ll") and _measure(w) > 1:
        w = w[:-1]
    return w
