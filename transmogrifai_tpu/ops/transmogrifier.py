"""Transmogrifier: automatic per-type vectorization dispatch.

Parity: reference ``core/.../stages/impl/feature/Transmogrifier.scala:52-352``
— ``.transmogrify()`` groups raw/derived features by type and applies each
group's default vectorizer, then combines everything with VectorsCombiner.
Reference defaults honored: TopK=20, MinSupport=10, 512 hash features,
TrackNulls=true, circular date representation.

Type routing (reference Transmogrifier case analysis):
  Real/RealNN/Currency/Percent        -> RealVectorizer (mean fill)
  Integral                            -> IntegralVectorizer (mode fill)
  Binary                              -> BinaryVectorizer
  Date/DateTime                       -> DateToUnitCircleVectorizer
  PickList/ComboBox/ID + Country/State/City/PostalCode/Street
                                      -> OneHotVectorizer (topK pivot)
  Text/TextArea/Email/URL/Phone/Base64-> TextHashingVectorizer
  MultiPickList                       -> SetVectorizer
  Geolocation                         -> GeolocationVectorizer
  OPVector                            -> passthrough to the combiner
  (SmartText* cardinality-adaptive vectorizers supersede the static text
  routing when enabled — see ops/smart_text.py.)
"""

from __future__ import annotations

from typing import Optional, Sequence

from transmogrifai_tpu.features.feature import FeatureLike
from transmogrifai_tpu.ops.combiner import VectorsCombiner
from transmogrifai_tpu.ops.vectorizers.dates import DateToUnitCircleVectorizer
from transmogrifai_tpu.ops.vectorizers.geolocation import GeolocationVectorizer
from transmogrifai_tpu.ops.vectorizers.hashing import TextHashingVectorizer
from transmogrifai_tpu.ops.vectorizers.numeric import (
    BinaryVectorizer, IntegralVectorizer, RealVectorizer,
)
from transmogrifai_tpu.ops.vectorizers.onehot import OneHotVectorizer, SetVectorizer
from transmogrifai_tpu.types import feature_types as ft

__all__ = ["transmogrify", "TransmogrifierDefaults"]


class TransmogrifierDefaults:
    """Reference defaults (Transmogrifier.scala:53-70)."""
    TOP_K = 20
    MIN_SUPPORT = 10
    NUM_HASH_FEATURES = 512
    MAX_NUM_HASH_FEATURES = 2 ** 17
    TRACK_NULLS = True
    DATE_TIME_PERIOD = "HourOfDay"


_PIVOT_TYPES = (ft.PickList, ft.ComboBox, ft.ID, ft.Country, ft.State,
                ft.City, ft.PostalCode, ft.Street)
_HASH_TYPES = (ft.Base64, ft.Email, ft.Phone, ft.URL, ft.TextArea, ft.Text)


def _route(f: FeatureLike) -> str:
    t = f.ftype
    if issubclass(t, (ft.Date,)):  # Date/DateTime before Integral
        return "date"
    if issubclass(t, ft.Binary):
        return "binary"
    if issubclass(t, ft.Integral):
        return "integral"
    if issubclass(t, ft.Real):  # Real/RealNN/Currency/Percent
        return "real"
    if issubclass(t, ft.MultiPickList):
        return "multipicklist"
    if issubclass(t, ft.Geolocation):
        return "geolocation"
    if issubclass(t, ft.OPVector):
        return "vector"
    if issubclass(t, _PIVOT_TYPES):
        return "pivot"
    if issubclass(t, ft.Text):
        return "hash"
    raise TypeError(
        f"Transmogrifier has no default vectorizer for {t.__name__} "
        f"(feature {f.name!r}); vectorize it explicitly")


def transmogrify(features: Sequence[FeatureLike],
                 top_k: int = TransmogrifierDefaults.TOP_K,
                 min_support: int = TransmogrifierDefaults.MIN_SUPPORT,
                 num_hash_features: int = TransmogrifierDefaults.NUM_HASH_FEATURES,
                 track_nulls: bool = TransmogrifierDefaults.TRACK_NULLS,
                 date_time_period: str = TransmogrifierDefaults.DATE_TIME_PERIOD,
                 ) -> FeatureLike:
    """Vectorize a heterogeneous feature set into one combined OPVector."""
    if not features:
        raise ValueError("transmogrify: no features given")
    groups: dict[str, list[FeatureLike]] = {}
    for f in features:
        groups.setdefault(_route(f), []).append(f)

    blocks: list[FeatureLike] = []
    order = ["real", "integral", "binary", "date", "pivot", "hash",
             "multipicklist", "geolocation", "vector"]
    for kind in order:
        fs = groups.get(kind)
        if not fs:
            continue
        if kind == "real":
            stage = RealVectorizer(track_nulls=track_nulls)
        elif kind == "integral":
            stage = IntegralVectorizer(track_nulls=track_nulls)
        elif kind == "binary":
            stage = BinaryVectorizer(track_nulls=track_nulls)
        elif kind == "date":
            stage = DateToUnitCircleVectorizer(
                time_period=date_time_period, track_nulls=track_nulls)
        elif kind == "pivot":
            stage = OneHotVectorizer(top_k=top_k, min_support=min_support,
                                     track_nulls=track_nulls)
        elif kind == "hash":
            stage = TextHashingVectorizer(num_features=num_hash_features,
                                          track_nulls=track_nulls)
        elif kind == "multipicklist":
            stage = SetVectorizer(top_k=top_k, min_support=min_support,
                                  track_nulls=track_nulls)
        elif kind == "geolocation":
            stage = GeolocationVectorizer(track_nulls=track_nulls)
        else:  # passthrough vectors
            blocks.extend(fs)
            continue
        blocks.append(fs[0].transform_with(stage, *fs[1:]))

    if len(blocks) == 1:
        return blocks[0]
    return blocks[0].transform_with(VectorsCombiner(), *blocks[1:])
