"""Transmogrifier: automatic per-type vectorization dispatch.

Parity: reference ``core/.../stages/impl/feature/Transmogrifier.scala:52-352``
— ``.transmogrify()`` groups raw/derived features by type and applies each
group's default vectorizer, then combines everything with VectorsCombiner.
Reference defaults honored: TopK=20, MinSupport=10, 512 hash features,
TrackNulls=true, circular date representation.

Type routing (reference Transmogrifier case analysis):
  Real/RealNN/Currency/Percent        -> RealVectorizer (mean fill)
  Integral                            -> IntegralVectorizer (mode fill)
  Binary                              -> BinaryVectorizer
  Date/DateTime                       -> DateToUnitCircleVectorizer
  PickList/ComboBox/ID + Country/State/City/PostalCode/Street
                                      -> OneHotVectorizer (topK pivot)
  Text/TextArea/Email/URL/Phone/Base64-> TextHashingVectorizer
  MultiPickList                       -> SetVectorizer
  Geolocation                         -> GeolocationVectorizer
  OPVector                            -> passthrough to the combiner
  (SmartText* cardinality-adaptive vectorizers supersede the static text
  routing when enabled — see ops/smart_text.py.)
"""

from __future__ import annotations

from typing import Optional, Sequence

from transmogrifai_tpu.features.feature import FeatureLike
from transmogrifai_tpu.ops.combiner import VectorsCombiner
from transmogrifai_tpu.ops.vectorizers.dates import DateToUnitCircleVectorizer
from transmogrifai_tpu.ops.vectorizers.geolocation import GeolocationVectorizer
from transmogrifai_tpu.ops.vectorizers.hashing import TextHashingVectorizer
from transmogrifai_tpu.ops.vectorizers.numeric import (
    BinaryVectorizer, IntegralVectorizer, RealVectorizer,
)
from transmogrifai_tpu.ops.vectorizers.onehot import OneHotVectorizer, SetVectorizer
from transmogrifai_tpu.types import feature_types as ft

__all__ = ["transmogrify", "TransmogrifierDefaults"]


class TransmogrifierDefaults:
    """Reference defaults (Transmogrifier.scala:53-70)."""
    TOP_K = 20
    MIN_SUPPORT = 10
    NUM_HASH_FEATURES = 512
    MAX_NUM_HASH_FEATURES = 2 ** 17
    TRACK_NULLS = True
    TRACK_INVALID = False
    MIN_INFO_GAIN = 0.01
    DATE_TIME_PERIOD = "HourOfDay"


_PIVOT_TYPES = (ft.PickList, ft.ComboBox, ft.ID, ft.Country, ft.State,
                ft.City, ft.PostalCode, ft.Street)
_HASH_TYPES = (ft.Base64, ft.Email, ft.Phone, ft.URL, ft.TextArea, ft.Text)


def _route(f: FeatureLike) -> str:
    t = f.ftype
    if issubclass(t, (ft.Date,)):  # Date/DateTime before Integral
        return "date"
    if issubclass(t, ft.Binary):
        return "binary"
    if issubclass(t, ft.Integral):
        return "integral"
    if issubclass(t, ft.Real):  # Real/RealNN/Currency/Percent
        return "real"
    if issubclass(t, ft.MultiPickList):
        return "multipicklist"
    if issubclass(t, ft.Geolocation) and not issubclass(t, ft.OPMap):
        return "geolocation"
    if issubclass(t, ft.OPVector):
        return "vector"
    # maps (before Text/lists — map types are not Text subclasses)
    if issubclass(t, (ft.DateMap,)):
        return "date_map"
    if issubclass(t, ft.IntegralMap):
        return "integral_map"
    if issubclass(t, ft.BinaryMap):
        return "binary_map"
    if issubclass(t, (ft.Prediction,)):
        raise TypeError("Prediction features are model outputs; "
                        "they cannot be transmogrified")
    if issubclass(t, ft.RealMap):
        return "real_map"
    if issubclass(t, ft.MultiPickListMap):
        return "multipicklist_map"
    if issubclass(t, ft.GeolocationMap):
        return "geolocation_map"
    if issubclass(t, (ft.TextMap,)):
        if t in (ft.TextMap, ft.TextAreaMap):
            return "smart_text_map"
        return "pivot_map"  # PickListMap, CountryMap, IDMap, ...
    if issubclass(t, ft.TextList):
        return "textlist"
    if issubclass(t, ft.DateList):
        return "datelist"
    if issubclass(t, ft.Email):
        return "email"
    if issubclass(t, ft.URL):
        return "url"
    if issubclass(t, ft.Phone):
        return "phone"
    if issubclass(t, ft.Base64):
        return "base64"
    if issubclass(t, _PIVOT_TYPES):
        return "pivot"
    if issubclass(t, ft.Text):
        return "smart_text"
    raise TypeError(
        f"Transmogrifier has no default vectorizer for {t.__name__} "
        f"(feature {f.name!r}); vectorize it explicitly")


def _join_tokens(tokens):
    return " ".join(tokens) if tokens else None


def transmogrify(features: Sequence[FeatureLike],
                 top_k: int = TransmogrifierDefaults.TOP_K,
                 min_support: int = TransmogrifierDefaults.MIN_SUPPORT,
                 num_hash_features: int = TransmogrifierDefaults.NUM_HASH_FEATURES,
                 track_nulls: bool = TransmogrifierDefaults.TRACK_NULLS,
                 date_time_period: str = TransmogrifierDefaults.DATE_TIME_PERIOD,
                 label: Optional[FeatureLike] = None,
                 track_invalid: bool = TransmogrifierDefaults.TRACK_INVALID,
                 min_info_gain: float = TransmogrifierDefaults.MIN_INFO_GAIN,
                 text_vectorizer: str = "smart",
                 ) -> FeatureLike:
    """Vectorize a heterogeneous feature set into one combined OPVector.

    ``text_vectorizer`` routes the free-text group: ``"smart"`` (default,
    the cardinality-adaptive SmartTextVectorizer), ``"hash"`` (host
    token-bag :class:`TextHashingVectorizer`), or ``"hash_device"``
    (round 14: :class:`DeviceTextHashingVectorizer` — categorical
    whole-value murmur hashing computed inside the fused device FE
    program; the right choice for Criteo-style high-cardinality id
    columns, where it removes the per-row host hashing loop entirely).

    ``label``: optional response feature enabling the reference's
    label-aware smart defaults (Transmogrifier.scala:99-104 passes the
    label through the numeric cases at :246-269):

    - Real/Currency/Percent/Integral scalars (NOT RealNN/Binary/Date) keep
      their mean/mode-fill block AND each gain a per-feature
      DecisionTreeNumericBucketizer block with ``trackNulls=false``
      (RichNumericFeature.scala:315-345 combines filled +: bucketized);
      features where the tree finds no informative split (minInfoGain
      gate) contribute no bucket columns.
    - Real/Currency/Percent/Integral MAPS are instead REPLACED by a per-key
      DecisionTreeNumericMapBucketizer with ``trackNulls`` kept
      (RichMapFeature.scala:607-625: ``case Some(lbl) => autoBucketize``);
      non-splitting keys contribute only their null-indicator column.
    """
    if not features:
        raise ValueError("transmogrify: no features given")
    groups: dict[str, list[FeatureLike]] = {}
    for f in features:
        groups.setdefault(_route(f), []).append(f)

    from transmogrifai_tpu.ops.parsers import (
        EmailToPickList, MimeTypeDetector, PhoneNumberParser, UrlToPickList,
    )
    from transmogrifai_tpu.ops.smart_text import SmartTextVectorizer
    from transmogrifai_tpu.ops.vectorizers.datelist import DateListVectorizer
    from transmogrifai_tpu.ops.vectorizers.maps import (
        BinaryMapVectorizer, DateMapToUnitCircleVectorizer,
        GeolocationMapVectorizer, IntegralMapVectorizer,
        MultiPickListMapVectorizer, RealMapVectorizer, SmartTextMapVectorizer,
        TextMapPivotVectorizer,
    )
    from transmogrifai_tpu.ops.vectorizers.bucketizers import (
        DecisionTreeNumericBucketizer, DecisionTreeNumericMapBucketizer,
    )
    from transmogrifai_tpu.stages.base import LambdaTransformer

    # derived routings: email/url -> domain picklist, phone -> validity
    # binary, base64 -> mime picklist (reference Transmogrifier case
    # analysis for these types)
    pivot_extra: list[FeatureLike] = []
    binary_extra: list[FeatureLike] = []
    for f in groups.pop("email", []):
        pivot_extra.append(f.transform_with(EmailToPickList()))
    for f in groups.pop("url", []):
        pivot_extra.append(f.transform_with(UrlToPickList()))
    for f in groups.pop("base64", []):
        pivot_extra.append(f.transform_with(MimeTypeDetector()))
    for f in groups.pop("phone", []):
        binary_extra.append(f.transform_with(PhoneNumberParser()))
    # textlists hash via joined tokens
    smart_extra: list[FeatureLike] = []
    for f in groups.pop("textlist", []):
        joined = f.transform_with(LambdaTransformer(
            _join_tokens, in_types=(ft.TextList,), out_type=ft.Text,
            operation_name="joinTokens"))
        smart_extra.append(joined)
    if pivot_extra:
        groups.setdefault("pivot", []).extend(pivot_extra)
    if binary_extra:
        groups.setdefault("binary", []).extend(binary_extra)
    if smart_extra:
        groups.setdefault("smart_text", []).extend(smart_extra)

    blocks: list[FeatureLike] = []
    order = ["real", "integral", "binary", "date", "pivot", "smart_text",
             "multipicklist", "geolocation", "datelist",
             "real_map", "integral_map", "binary_map", "date_map",
             "pivot_map", "smart_text_map", "multipicklist_map",
             "geolocation_map", "vector"]
    for kind in order:
        fs = groups.get(kind)
        if not fs:
            continue
        if label is not None and kind in ("real_map", "integral_map"):
            # reference RichMapFeature.scala:620-625: with a label the
            # numeric-map vectorizer is REPLACED by per-key tree buckets
            for f in fs:
                blocks.append(label.transform_with(
                    DecisionTreeNumericMapBucketizer(
                        min_info_gain=min_info_gain,
                        track_nulls=track_nulls,
                        track_invalid=track_invalid), f))
            continue
        if kind == "real":
            stage = RealVectorizer(track_nulls=track_nulls)
        elif kind == "integral":
            stage = IntegralVectorizer(track_nulls=track_nulls)
        elif kind == "binary":
            stage = BinaryVectorizer(track_nulls=track_nulls)
        elif kind == "date":
            stage = DateToUnitCircleVectorizer(
                time_period=date_time_period, track_nulls=track_nulls)
        elif kind == "pivot":
            stage = OneHotVectorizer(top_k=top_k, min_support=min_support,
                                     track_nulls=track_nulls)
        elif kind == "smart_text":
            if text_vectorizer == "hash_device":
                from transmogrifai_tpu.ops.vectorizers.hashing import (
                    DeviceTextHashingVectorizer,
                )
                stage = DeviceTextHashingVectorizer(
                    num_features=num_hash_features, track_nulls=track_nulls)
            elif text_vectorizer == "hash":
                stage = TextHashingVectorizer(
                    num_features=num_hash_features, track_nulls=track_nulls)
            elif text_vectorizer == "smart":
                stage = SmartTextVectorizer(
                    top_k=top_k, min_support=min_support,
                    num_hash_features=num_hash_features,
                    track_nulls=track_nulls)
            else:
                raise ValueError(
                    f"text_vectorizer={text_vectorizer!r}; one of "
                    "smart|hash|hash_device")
        elif kind == "multipicklist":
            stage = SetVectorizer(top_k=top_k, min_support=min_support,
                                  track_nulls=track_nulls)
        elif kind == "geolocation":
            stage = GeolocationVectorizer(track_nulls=track_nulls)
        elif kind == "datelist":
            stage = DateListVectorizer(track_nulls=track_nulls)
        elif kind == "real_map":
            stage = RealMapVectorizer(track_nulls=track_nulls)
        elif kind == "integral_map":
            stage = IntegralMapVectorizer(track_nulls=track_nulls)
        elif kind == "binary_map":
            stage = BinaryMapVectorizer(track_nulls=track_nulls)
        elif kind == "date_map":
            stage = DateMapToUnitCircleVectorizer(
                time_period=date_time_period, track_nulls=track_nulls)
        elif kind == "pivot_map":
            stage = TextMapPivotVectorizer(
                top_k=top_k, min_support=min_support, track_nulls=track_nulls)
        elif kind == "smart_text_map":
            stage = SmartTextMapVectorizer(
                top_k=top_k, min_support=min_support,
                track_nulls=track_nulls)
        elif kind == "multipicklist_map":
            stage = MultiPickListMapVectorizer(
                top_k=top_k, min_support=min_support, track_nulls=track_nulls)
        elif kind == "geolocation_map":
            stage = GeolocationMapVectorizer(track_nulls=track_nulls)
        else:  # passthrough vectors
            blocks.extend(fs)
            continue
        blocks.append(fs[0].transform_with(stage, *fs[1:]))
        if label is not None and kind in ("real", "integral"):
            # reference RichNumericFeature.scala:315-345: the mean/mode-fill
            # block stays AND each feature gains a tree-bucket block
            # (trackNulls=false there — the fill block already tracks).
            # RealNN takes no label in the reference case analysis (:270).
            for f in fs:
                if issubclass(f.ftype, ft.RealNN):
                    continue
                blocks.append(label.transform_with(
                    DecisionTreeNumericBucketizer(
                        min_info_gain=min_info_gain, track_nulls=False,
                        track_invalid=track_invalid), f))

    if len(blocks) == 1:
        return blocks[0]
    return blocks[0].transform_with(VectorsCombiner(), *blocks[1:])
