"""Calendar time-period extraction from epoch-millis date features.

Parity: reference ``features/.../impl/feature/TimePeriod.scala`` (enum of
DayOfMonth/DayOfWeek/DayOfYear/HourOfDay/MonthOfYear/WeekOfMonth/WeekOfYear,
weeks numbered per ``java.time.WeekFields.of(MONDAY, 1)``: Monday-first,
minimalDays=1 — NOT ISO-8601's minimalDays=4) and ``core/.../impl/feature/
TimePeriod{,List,Map}Transformer.scala``. All UTC, like the reference's
default zone.

Exact calendar integers need 64-bit epoch millis, which the (x64-disabled)
device path cannot carry — so these are vectorized int64 *host* kernels
(civil-from-days integer arithmetic over whole numpy columns, no per-row
datetime objects). The device-side cyclic encoding of the same periods
lives in ``vectorizers/dates.py``, where phase precision suffices.
"""

from __future__ import annotations

from enum import Enum
from typing import Optional

import numpy as np

from transmogrifai_tpu import frame as fr
from transmogrifai_tpu.stages.base import HostTransformer
from transmogrifai_tpu.types import feature_types as ft

__all__ = ["TimePeriod", "TimePeriodTransformer", "TimePeriodListTransformer",
           "TimePeriodMapTransformer"]


def _civil_from_millis(ms: np.ndarray):
    """Vectorized epoch-millis -> (year, month, day, hour, day_of_week
    Mon=1..Sun=7, day_of_year). Howard Hinnant's civil_from_days, branch-free
    over int64 arrays."""
    ms = np.asarray(ms, np.int64)
    days = np.floor_divide(ms, 86_400_000)
    secs = np.floor_divide(ms - days * 86_400_000, 1000)
    hour = np.floor_divide(secs, 3600)
    z = days + 719_468
    era = np.floor_divide(z, 146_097)
    doe = z - era * 146_097
    yoe = np.floor_divide(
        doe - np.floor_divide(doe, 1460) + np.floor_divide(doe, 36_524)
        - np.floor_divide(doe, 146_096), 365)
    y = yoe + era * 400
    doy_mar = doe - (365 * yoe + np.floor_divide(yoe, 4)
                     - np.floor_divide(yoe, 100))          # [0, 365]
    mp = np.floor_divide(5 * doy_mar + 2, 153)
    day = doy_mar - np.floor_divide(153 * mp + 2, 5) + 1
    month = mp + np.where(mp < 10, 3, -9)
    year = y + np.where(month <= 2, 1, 0)
    # ISO day-of-week: 1970-01-01 was a Thursday (=4)
    dow = ((days + 3) % 7) + 1
    # day-of-year via days since Jan 1 of `year`
    y1 = year - 1
    jan1 = (365 * y1 + np.floor_divide(y1, 4) - np.floor_divide(y1, 100)
            + np.floor_divide(y1, 400)) - 719_162
    doy = days - jan1 + 1
    return year, month, day, hour, dow, doy


def _week_fields(day_in_period, dow):
    """Week number with Monday-start weeks, minimalDays=1 (java WeekFields
    .of(MONDAY, 1)): week = ceil((day + offset)/7) where offset is the
    Monday-aligned weekday of day 1 of the period."""
    first_dow = ((dow - 1) - (day_in_period - 1)) % 7      # Mon=0 of day 1
    return np.floor_divide(day_in_period + first_dow - 1, 7) + 1


class TimePeriod(Enum):
    DayOfMonth = "DayOfMonth"
    DayOfWeek = "DayOfWeek"
    DayOfYear = "DayOfYear"
    HourOfDay = "HourOfDay"
    MonthOfYear = "MonthOfYear"
    WeekOfMonth = "WeekOfMonth"
    WeekOfYear = "WeekOfYear"

    def extract(self, millis):
        """Vectorized extraction over an array of epoch millis."""
        year, month, day, hour, dow, doy = _civil_from_millis(millis)
        if self is TimePeriod.DayOfMonth:
            return day
        if self is TimePeriod.DayOfWeek:
            return dow
        if self is TimePeriod.DayOfYear:
            return doy
        if self is TimePeriod.HourOfDay:
            return hour
        if self is TimePeriod.MonthOfYear:
            return month
        if self is TimePeriod.WeekOfMonth:
            return _week_fields(day, dow)
        return _week_fields(doy, dow)                      # WeekOfYear

    def extract_int(self, millis: int) -> int:
        return int(self.extract(np.asarray([millis], np.int64))[0])


class TimePeriodTransformer(HostTransformer):
    """Date -> Integral period value (reference dateToTimePeriod)."""

    in_types = (ft.Date,)
    out_type = ft.Integral

    def __init__(self, period="DayOfMonth", uid: Optional[str] = None):
        self.period = (period.value if isinstance(period, TimePeriod)
                       else str(period))
        super().__init__(uid=uid)

    def _period(self) -> TimePeriod:
        return TimePeriod(self.period)

    def host_apply(self, *cols: fr.HostColumn) -> fr.HostColumn:
        col = cols[0]
        # python_value applies the null mask — numeric-backed date columns
        # store masked slots as 0 in .values, which must stay None here
        raw = [col.python_value(i) for i in range(len(col))]
        vals = np.asarray([0 if v is None else int(v) for v in raw],
                          np.int64)
        out = self._period().extract(vals)
        return fr.HostColumn.from_values(
            ft.Integral,
            [int(out[i]) if raw[i] is not None else None
             for i in range(len(col))])

    def transform_row(self, value):
        if value is None:
            return None
        return self._period().extract_int(int(value))


class TimePeriodListTransformer(HostTransformer):
    """DateList -> OPVector of per-event period values (reference
    dateListToTimePeriod)."""

    in_types = (ft.DateList,)
    out_type = ft.OPVector

    def __init__(self, period="DayOfMonth", uid: Optional[str] = None):
        self.period = (period.value if isinstance(period, TimePeriod)
                       else str(period))
        super().__init__(uid=uid)

    def transform_row(self, value):
        if not value:
            return np.zeros(0, np.float32)
        p = TimePeriod(self.period)
        return p.extract(np.asarray(list(value), np.int64)).astype(np.float32)

    def host_apply(self, *cols: fr.HostColumn) -> fr.HostColumn:
        # Rows have one period value per event, so widths vary (the reference
        # emits variable-length Spark vectors). The columnar frame needs one
        # static width: pad each row with zeros to the batch max.
        rows = [self.transform_row(cols[0].python_value(i))
                for i in range(len(cols[0]))]
        width = max((r.shape[0] for r in rows), default=0)
        out = np.zeros((len(rows), width), np.float32)
        for i, r in enumerate(rows):
            out[i, :r.shape[0]] = r
        return fr.HostColumn(ft.OPVector, out, None)


class TimePeriodMapTransformer(HostTransformer):
    """DateMap -> IntegralMap of per-key period values (reference
    dateMapToTimePeriod)."""

    in_types = (ft.DateMap,)
    out_type = ft.IntegralMap

    def __init__(self, period="DayOfMonth", uid: Optional[str] = None):
        self.period = (period.value if isinstance(period, TimePeriod)
                       else str(period))
        super().__init__(uid=uid)

    def transform_row(self, value):
        if not value:
            return {}
        p = TimePeriod(self.period)
        return {k: p.extract_int(int(v)) for k, v in value.items()
                if v is not None}
