"""Scatter-add (node, feature, bin) gradient/hessian histograms.

The tree learner's per-level op in its GSPMD-safe form: one flat-index
scatter-add over the binned matrix. Under a mesh the scatter runs per
shard and XLA inserts the psum (the analog of XGBoost's Rabit
all-reduce / Spark MLlib's executor histogram aggregation, SURVEY §2.7
P5). On a single chip at large row counts the sorted MXU engine in
``models/trees._grow_tree_sorted`` replaces it — host-fenced chip
measurements put this scatter at ~24 ms per stat per 100k x 28 x 64
(~0.9 GB/s, serialized) versus ~80 ms per LEVEL for the sorted block
contraction at 1M rows.

History: an earlier Pallas compare+matmul kernel lived beside this
(``ops/histogram_pallas.py``, rounds 1-4) for levels with <= 8 nodes.
Its justifying on-chip numbers turned out to be enqueue-time artifacts
(``block_until_ready`` is not a fence on the axon backend — see
benchmarks/_timing.py); re-measured with host-fetch fences its niche
(sub-ms shallow levels of the small-fit path) was irrelevant, and the
sorted-path kernel (``ops/sorted_hist_pallas.py``) supersedes it as the
measured Pallas variant. Deleted in round 5: benchmark-or-delete,
resolved by deletion with data.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

__all__ = ["node_bin_histogram_xla"]


@functools.partial(jax.jit, static_argnames=("n_nodes", "n_bins"))
def node_bin_histogram_xla(Xb, node, grad, hess, *, n_nodes: int,
                           n_bins: int):
    """[n_nodes, d, B] grad and hess histograms via flat-index scatter.

    Xb: [n, d] int32 bin codes in [0, B); node: [n] int32 in
    [0, n_nodes); grad/hess: [n] f32 (row weights already applied).
    """
    n, d = Xb.shape
    flat = ((node[:, None] * d + jnp.arange(d)[None, :]) * n_bins
            + Xb).reshape(-1)
    seg = n_nodes * d * n_bins
    hg = jnp.zeros(seg, jnp.float32).at[flat].add(
        jnp.broadcast_to(grad[:, None], (n, d)).reshape(-1))
    hh = jnp.zeros(seg, jnp.float32).at[flat].add(
        jnp.broadcast_to(hess[:, None], (n, d)).reshape(-1))
    return (hg.reshape(n_nodes, d, n_bins), hh.reshape(n_nodes, d, n_bins))
