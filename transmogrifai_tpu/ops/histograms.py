"""Scatter-add (node, feature, bin) gradient/hessian histograms.

The tree learner's per-level op in its GSPMD-safe form: one flat-index
scatter-add over the binned matrix. Under a mesh the scatter runs per
shard and XLA inserts the psum (the analog of XGBoost's Rabit
all-reduce / Spark MLlib's executor histogram aggregation, SURVEY §2.7
P5). On a single chip at large row counts the sorted MXU engine in
``models/trees._grow_tree_sorted`` replaces it — host-fenced chip
measurements put this scatter at ~24 ms per stat per 100k x 28 x 64
(~0.9 GB/s, serialized) versus ~80 ms per LEVEL for the sorted block
contraction at 1M rows.

Batched shape (round 8, the fold x grid-stacked tree sweep): the public
function carries a ``jax.custom_batching.custom_vmap`` rule that FOLDS
every vmapped axis into the node axis — a [B]-batched call lowers to ONE
flat-index scatter over ``B * n_nodes`` logical nodes instead of a
B-times-serialized batched scatter. The fold/lane/class vmaps of the
stacked tree trainer compose: each level folds again, so the whole
(k folds x L lanes x n_out classes) batch is still a single scatter per
level. (The sorted engine needs no such rule: its one-hot contraction is
a batched einsum whose extra axes feed the MXU batch dims directly.)
The rule changes only the lowering, not the math — per batch slice the
update order is row order either way, so results are bit-identical to
the unbatched call.

History: an earlier Pallas compare+matmul kernel lived beside this
(``ops/histogram_pallas.py``, rounds 1-4) for levels with <= 8 nodes.
Its justifying on-chip numbers turned out to be enqueue-time artifacts
(``block_until_ready`` is not a fence on the axon backend — see
benchmarks/_timing.py); re-measured with host-fetch fences its niche
(sub-ms shallow levels of the small-fit path) was irrelevant, and the
sorted-path kernel (``ops/sorted_hist_pallas.py``) supersedes it as the
measured Pallas variant. Deleted in round 5: benchmark-or-delete,
resolved by deletion with data.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

__all__ = ["node_bin_histogram_xla"]


@functools.lru_cache(maxsize=None)
def _hist_fn(n_nodes: int, n_bins: int):
    """The (n_nodes, n_bins)-specialized scatter histogram with its
    batch-folding vmap rule. Cached so the custom_vmap wrapper (and its
    jit traces) are built once per static shape."""
    from jax.custom_batching import custom_vmap

    @custom_vmap
    def hist(Xb, node, grad, hess):
        n, d = Xb.shape
        flat = ((node[:, None] * d + jnp.arange(d)[None, :]) * n_bins
                + Xb).reshape(-1)
        seg = n_nodes * d * n_bins
        hg = jnp.zeros(seg, jnp.float32).at[flat].add(
            jnp.broadcast_to(grad[:, None], (n, d)).reshape(-1))
        hh = jnp.zeros(seg, jnp.float32).at[flat].add(
            jnp.broadcast_to(hess[:, None], (n, d)).reshape(-1))
        return (hg.reshape(n_nodes, d, n_bins),
                hh.reshape(n_nodes, d, n_bins))

    @hist.def_vmap
    def _batched(axis_size, in_batched, Xb, node, grad, hess):
        # fold the vmapped axis into the node axis: one flat scatter over
        # axis_size * n_nodes logical nodes. Unbatched operands (e.g. the
        # shared bin codes under the stacked sweep's lane vmap) broadcast
        # — XLA fuses the broadcast into the scatter's index computation.
        bsz = axis_size

        def bc(a, was_batched):
            return a if was_batched else jnp.broadcast_to(
                a, (bsz,) + a.shape)

        Xb2 = bc(Xb, in_batched[0])
        node2 = bc(node, in_batched[1])
        g2 = bc(grad, in_batched[2])
        h2 = bc(hess, in_batched[3])
        n, d = Xb2.shape[1], Xb2.shape[2]
        off = (jnp.arange(bsz, dtype=node2.dtype) * n_nodes)[:, None]
        hg, hh = _hist_fn(bsz * n_nodes, n_bins)(
            Xb2.reshape(bsz * n, d), (node2 + off).reshape(-1),
            g2.reshape(-1), h2.reshape(-1))
        return (hg.reshape(bsz, n_nodes, d, n_bins),
                hh.reshape(bsz, n_nodes, d, n_bins)), (True, True)

    return hist


@functools.partial(jax.jit, static_argnames=("n_nodes", "n_bins"))
def node_bin_histogram_xla(Xb, node, grad, hess, *, n_nodes: int,
                           n_bins: int):
    """[n_nodes, d, B] grad and hess histograms via flat-index scatter.

    Xb: [n, d] integer bin codes in [0, B) (int8 codes promote in the
    flat-index arithmetic); node: [n] int32 in [0, n_nodes); grad/hess:
    [n] f32 (row weights already applied). Safe under ``vmap`` at any
    nesting depth: the batch axes fold into the node axis (module
    docstring) so the lowering stays one scatter.
    """
    return _hist_fn(int(n_nodes), int(n_bins))(Xb, node, grad, hess)
