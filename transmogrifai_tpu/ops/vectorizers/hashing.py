"""Hashing-trick vectorizer for unbounded-cardinality text.

Parity: reference ``core/.../stages/impl/feature/OPCollectionHashingVectorizer
.scala`` / ``OpHashingTF.scala`` — tokens hash into a fixed number of bins
(default 512, max 2^17 in the reference Transmogrifier defaults), shared or
separate hash space per input, optional binary (presence) vs count values,
plus a null-indicator per input.

Host/device split (SURVEY §7 hard part #2): tokenization + hashing are
string work and run on host into a dense [n, bins] block; everything
downstream consumes the device VectorColumn. The hash is crc32 (stable,
seedable by bin count) — numeric parity with Spark's murmur3 is not a
behavioral contract, bin distribution quality is.
"""

from __future__ import annotations

import re
import zlib
from typing import Optional, Sequence

import numpy as np

from transmogrifai_tpu import frame as fr
from transmogrifai_tpu.stages.base import DeviceTransformer, HostTransformer
from transmogrifai_tpu.types import feature_types as ft
from transmogrifai_tpu.vector_metadata import (
    parent_of,
    NULL_INDICATOR, VectorColumnMetadata, VectorMetadata,
)

__all__ = ["TextHashingVectorizer", "DeviceTextHashingVectorizer",
           "hash_token", "encode_ascii_rows"]

_TOKEN_RE = re.compile(r"[^\W_]+", re.UNICODE)

#: ctypes handle to the native tokenizer+hasher (None -> pure Python)
_native_lib = None
_native_tried = False


def _native():
    """Build/load the C++ tokenizer-hasher once (None when unavailable).
    Registers BOTH entry points (per-row batch + corpus histogram) so every
    consumer shares one loader and one tokenizer contract."""
    global _native_lib, _native_tried
    if not _native_tried:
        _native_tried = True
        from transmogrifai_tpu.native import build_and_load
        lib = build_and_load("text_hashing.cpp", "texthash")
        if lib is not None:
            import ctypes
            i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
            lib.hash_tokens_batch.argtypes = [
                ctypes.c_char_p, i64p,
                ctypes.c_int64, ctypes.c_int32, ctypes.c_int32,
                ctypes.c_int32,
                np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS"),
                ctypes.c_int64, ctypes.c_int64,
            ]
            lib.hash_tokens_batch.restype = None
            lib.hash_tokens_hist.argtypes = [
                ctypes.c_char_p, i64p,
                ctypes.c_int64, ctypes.c_int32, ctypes.c_int32,
                np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS"),
            ]
            lib.hash_tokens_hist.restype = None
        _native_lib = lib
    return _native_lib


#: native-eligibility row-length cap (protects the C 4096-byte token buffer
#: with margin; longer rows take the Python path)
_NATIVE_MAX_LEN = 4000


def encode_ascii_rows(values) -> Optional[tuple[bytes, np.ndarray, int]]:
    """(concatenated buffer, [n+1] offsets, null count) for the native
    tokenizer, or None when any row is ineligible (non-str/non-ASCII/too
    long — parity with the Python regex path is a contract). Shared by the
    vectorizer and the RawFeatureFilter distribution pass."""
    if not all(v is None or (isinstance(v, str) and v.isascii()
                             and len(v) <= _NATIVE_MAX_LEN) for v in values):
        return None
    n = len(values)
    parts: list[bytes] = []
    lens = np.zeros(n + 1, dtype=np.int64)
    nulls = 0
    for r in range(n):
        v = values[r]
        if v is None:
            nulls += 1
            continue  # zero-length row: no tokens
        b = v.encode("ascii")
        parts.append(b)
        lens[r + 1] = len(b)
    return b"".join(parts), np.cumsum(lens).astype(np.int64), nulls


def hash_token(token: str, num_bins: int) -> int:
    return zlib.crc32(token.encode("utf-8")) % num_bins


def tokenize(text: str, lowercase: bool = True) -> list[str]:
    if lowercase:
        text = text.lower()
    if text.isascii():
        return _TOKEN_RE.findall(text)
    # space-less scripts (CJK/Thai) segment into character bigrams; the
    # script-aware analyzer lives with the text chain (never reaches the
    # native path, which is ASCII-only by contract)
    from transmogrifai_tpu.ops.text import simple_tokenize
    return simple_tokenize(text, lowercase=False)


class TextHashingVectorizer(HostTransformer):
    """N text inputs -> [n, N*(bins[+1])] hashed token counts."""

    variadic = True
    in_types = (ft.Text,)
    out_type = ft.OPVector

    def __init__(self, num_features: int = 512, binary_freq: bool = False,
                 lowercase: bool = True, track_nulls: bool = True,
                 shared_hash_space: bool = False,
                 uid: Optional[str] = None):
        self.num_features = num_features
        self.binary_freq = binary_freq
        self.lowercase = lowercase
        self.track_nulls = track_nulls
        self.shared_hash_space = shared_hash_space
        super().__init__(uid=uid)

    # -- hashing core --------------------------------------------------------
    def _accumulate(self, text: Optional[str], row: np.ndarray, offset: int):
        if text is None:
            return
        for tok in tokenize(text, self.lowercase):
            b = offset + hash_token(tok, self.num_features)
            if self.binary_freq:
                row[b] = 1.0
            else:
                row[b] += 1.0

    def _layout(self, n_inputs: int) -> tuple[int, list[int], int]:
        """(hash_width, per-input offsets, total_width)."""
        if self.shared_hash_space:
            hash_width = self.num_features
            offsets = [0] * n_inputs
        else:
            hash_width = self.num_features * n_inputs
            offsets = [self.num_features * i for i in range(n_inputs)]
        total = hash_width + (n_inputs if self.track_nulls else 0)
        return hash_width, offsets, total

    def transform_row(self, *values):
        hash_width, offsets, total = self._layout(len(values))
        row = np.zeros(total, dtype=np.float32)
        for i, v in enumerate(values):
            self._accumulate(v, row, offsets[i])
            if self.track_nulls and v is None:
                row[hash_width + i] = 1.0
        return row

    def _native_column(self, col: fr.HostColumn, out: np.ndarray,
                       col_offset: int) -> bool:
        """Hash one column via the C++ path. Returns False when the column
        needs the Python path (non-ASCII text or very long rows — the
        native tokenizer is exact only for ASCII; parity with the Python
        row path is a contract)."""
        lib = _native()
        if lib is None:
            return False
        encoded = encode_ascii_rows(col.values)
        if encoded is None:
            return False
        buf, offsets, _ = encoded
        lib.hash_tokens_batch(
            buf, offsets, np.int64(len(col)),
            np.int32(self.num_features), np.int32(self.lowercase),
            np.int32(self.binary_freq), out, np.int64(out.shape[1]),
            np.int64(col_offset))
        return True

    def host_apply(self, *cols: fr.HostColumn) -> fr.HostColumn:
        n = len(cols[0])
        hash_width, offsets, total = self._layout(len(cols))
        out = np.zeros((n, total), dtype=np.float32)
        for i, col in enumerate(cols):
            if not self._native_column(col, out, offsets[i]):
                for r in range(n):
                    self._accumulate(col.values[r], out[r], offsets[i])
            if self.track_nulls:
                for r in range(n):
                    if col.values[r] is None:
                        out[r, hash_width + i] = 1.0
        return fr.HostColumn(ft.OPVector, out, meta=self._meta(len(cols)))

    def _meta(self, n_inputs: int) -> VectorMetadata:
        feats = self.input_features
        hash_width, offsets, _ = self._layout(n_inputs)
        cols = []
        if self.shared_hash_space:
            all_names = tuple(f.name for f in feats)
            all_types = tuple(f.ftype.__name__ for f in feats)
            for j in range(self.num_features):
                cols.append(VectorColumnMetadata(
                    all_names, all_types, grouping=None,
                    descriptor_value=f"hash_{j}"))
        else:
            for f in feats:
                for j in range(self.num_features):
                    cols.append(VectorColumnMetadata(
                        *parent_of(f), grouping=f.name,
                        descriptor_value=f"hash_{j}"))
        if self.track_nulls:
            for f in feats:
                cols.append(VectorColumnMetadata(
                    *parent_of(f), grouping=f.name,
                    indicator_value=NULL_INDICATOR))
        return VectorMetadata(self.get_output().name, tuple(cols)).reindexed(0)


class DeviceTextHashingVectorizer(DeviceTransformer):
    """Device-resident categorical feature hashing: N text inputs ->
    [n, N*bins (+N)] hashed one-hot counts, computed INSIDE the fused FE
    program (round 14).

    Semantics: each value hashes as ONE token (murmur3 x86_32 of its
    UTF-8 bytes — ``ops/hashing_pallas.murmur3_str``) — the categorical
    hashing-trick (Criteo-style high-cardinality id columns), not the
    token-bag hashing of :class:`TextHashingVectorizer` (which stays the
    right choice for free text). Layout matches the host vectorizer:
    per-input hash blocks first, then one null-indicator column per input.

    Execution split: hashing is per-UNIQUE — a trace-time murmur3 table
    over the column's dictionary vocab (aux data, exactly
    ``OneHotModel``'s category-table idiom, so the jit key moves only
    when the vocab does) — while the per-ROW work (the O(n x bins)
    one-hot accumulate the host vectorizer paid in Python) runs on
    device through ``ops/hashing_pallas.segment_onehot`` (Pallas kernel
    on TPU, XLA fallback elsewhere; bitwise-identical)."""

    variadic = True
    in_types = (ft.Text,)
    out_type = ft.OPVector

    def __init__(self, num_features: int = 512, track_nulls: bool = True,
                 seed: int = 0, uid: Optional[str] = None):
        self.num_features = num_features
        self.track_nulls = track_nulls
        self.seed = seed
        super().__init__(uid=uid)

    def _vocab_bins(self, vocab: Sequence[str]) -> np.ndarray:
        from transmogrifai_tpu.ops.hashing_pallas import murmur3_str
        if not vocab:
            return np.zeros(1, np.int32)
        return np.fromiter(
            (murmur3_str(v, self.seed) % self.num_features for v in vocab),
            np.int32, count=len(vocab))

    def device_apply(self, params, *cols: fr.CodesColumn) -> fr.VectorColumn:
        import jax.numpy as jnp

        from transmogrifai_tpu.ops.hashing_pallas import segment_onehot
        B = self.num_features
        blocks = []
        nulls = []
        for c in cols:
            table = jnp.asarray(self._vocab_bins(c.vocab))
            bins = jnp.where(c.codes >= 0, table[jnp.clip(c.codes, 0)],
                             jnp.int32(-1))
            blocks.append(segment_onehot(bins[:, None], B))
            if self.track_nulls:
                nulls.append((c.codes < 0).astype(jnp.float32)[:, None])
        parts = blocks + nulls
        out = parts[0] if len(parts) == 1 else jnp.concatenate(parts, axis=1)
        return fr.VectorColumn(out, self._meta(len(cols)))

    def transform_row(self, *values):
        from transmogrifai_tpu.ops.hashing_pallas import murmur3_str
        B = self.num_features
        n = len(values)
        width = n * B + (n if self.track_nulls else 0)
        row = np.zeros(width, np.float32)
        for i, v in enumerate(values):
            if v is None:
                if self.track_nulls:
                    row[n * B + i] = 1.0
            else:
                row[i * B + murmur3_str(v, self.seed) % B] += 1.0
        return row

    def _meta(self, n_inputs: int) -> VectorMetadata:
        feats = self.input_features
        cols = []
        for f in feats:
            for j in range(self.num_features):
                cols.append(VectorColumnMetadata(
                    *parent_of(f), grouping=f.name,
                    descriptor_value=f"hash_{j}"))
        if self.track_nulls:
            for f in feats:
                cols.append(VectorColumnMetadata(
                    *parent_of(f), grouping=f.name,
                    indicator_value=NULL_INDICATOR))
        return VectorMetadata(self.get_output().name, tuple(cols)).reindexed(0)
