"""Numeric bucketizers: fixed-split, decision-tree-driven, and percentile.

Parity: reference ``core/.../stages/impl/feature/NumericBucketizer.scala``
(fixed splits -> one-hot bucket block with optional invalid/null tracking),
``DecisionTreeNumericBucketizer.scala`` (fits a single-feature decision tree
against the label; the tree's thresholds become the splits; no informative
split -> passthrough empty block), ``DecisionTreeNumericMapBucketizer.scala``
(same per map key) and ``PercentileCalibrator.scala`` (empirical quantile
mapping onto [0, buckets-1]).

TPU-first: bucketization at transform time is a ``searchsorted`` + one-hot
gather fused into the layer program (MXU-friendly one-hot matmul consumers);
the split *search* at fit time is a host-side exact scan over quantile
candidates — fitting happens once, scoring is the hot path.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from transmogrifai_tpu import frame as fr
from transmogrifai_tpu.stages.base import (
    AllowLabelAsInput, DeviceTransformer, Estimator, HostTransformer,
)
from transmogrifai_tpu.types import feature_types as ft
from transmogrifai_tpu.vector_metadata import (
    NULL_INDICATOR, VectorColumnMetadata, VectorMetadata, parent_of,
)

__all__ = [
    "NumericBucketizer", "DecisionTreeNumericBucketizer",
    "DecisionTreeNumericMapBucketizer", "PercentileCalibrator",
]

#: indicator for values outside the split range (reference trackInvalid)
INVALID_INDICATOR = "InvalidIndicatorValue"


def bucket_labels(splits: Sequence[float]) -> list[str]:
    """Human-readable interval labels "lo-hi" per bucket."""
    def s(x: float) -> str:
        if np.isneginf(x):
            return "-Inf"
        if np.isposinf(x):
            return "Inf"
        return f"{x:.6g}"
    return [f"{s(a)}-{s(b)}" for a, b in zip(splits[:-1], splits[1:])]


def _bucket_meta(out_name, feature, labels: Sequence[str], track_invalid: bool,
                 track_nulls: bool, grouping: Optional[str] = None
                 ) -> list[VectorColumnMetadata]:
    group = grouping or feature.name
    cols = [VectorColumnMetadata(*parent_of(feature), grouping=group,
                                 indicator_value=lb) for lb in labels]
    if track_invalid:
        cols.append(VectorColumnMetadata(*parent_of(feature), grouping=group,
                                         indicator_value=INVALID_INDICATOR))
    if track_nulls:
        cols.append(VectorColumnMetadata(*parent_of(feature), grouping=group,
                                         indicator_value=NULL_INDICATOR))
    return cols


def _bucketize_block(values, mask, splits: np.ndarray, track_invalid: bool,
                     track_nulls: bool):
    """Jittable: one-hot bucket block for one numeric column.

    Layout: [bucket_0..bucket_{k-1}, invalid?, null?] — a present value in
    [splits[i], splits[i+1]) lights bucket i; out-of-range lights the invalid
    column (or nothing); missing lights the null column (or nothing).

    Engine-dispatched (round 14): on TPU backends the bin-edge search +
    one-hot expand run as a Pallas kernel fully in VMEM
    (``ops/quantile_bin_pallas.py``); everywhere else (and under
    ``TRANSMOGRIFAI_BUCKET_ENGINE=xla``) the original XLA path runs —
    CPU CI asserts bitwise parity between the two.
    """
    from transmogrifai_tpu.ops.quantile_bin_pallas import bucketize_block
    return bucketize_block(values, mask, splits, track_invalid, track_nulls)


class NumericBucketizer(DeviceTransformer):
    """Fixed-split bucketizer: one numeric feature -> one-hot bucket block.

    Splits must be strictly increasing and cover the expected range; pass
    ``-inf``/``inf`` ends for total coverage.
    """

    in_types = (ft.Real,)
    out_type = ft.OPVector

    def __init__(self, splits: Sequence[float] = (float("-inf"), 0.0, float("inf")),
                 track_nulls: bool = True, track_invalid: bool = False,
                 labels: Optional[Sequence[str]] = None,
                 uid: Optional[str] = None):
        sp = [float(s) for s in splits]
        if len(sp) < 2 or any(a >= b for a, b in zip(sp[:-1], sp[1:])):
            raise ValueError(f"splits must be strictly increasing, got {sp}")
        self.splits = sp
        self.track_nulls = track_nulls
        self.track_invalid = track_invalid
        self.labels = list(labels) if labels is not None else bucket_labels(sp)
        if len(self.labels) != len(sp) - 1:
            raise ValueError("need one label per bucket")
        super().__init__(uid=uid)

    def device_apply(self, params, col: fr.NumericColumn) -> fr.VectorColumn:
        block = _bucketize_block(col.values, col.mask,
                                 np.asarray(self.splits, np.float64),
                                 self.track_invalid, self.track_nulls)
        meta = VectorMetadata(self.get_output().name, tuple(_bucket_meta(
            self.get_output().name, self.input_features[0], self.labels,
            self.track_invalid, self.track_nulls))).reindexed(0)
        return fr.VectorColumn(block, meta)

    def transform_row(self, value):
        k = len(self.splits) - 1
        width = k + int(self.track_invalid) + int(self.track_nulls)
        out = np.zeros(width, np.float32)
        if value is None:
            if self.track_nulls:
                out[k + int(self.track_invalid)] = 1.0
            return out
        v = float(value)
        if v < self.splits[0] or v > self.splits[-1]:
            if self.track_invalid:
                out[k] = 1.0
            return out
        idx = int(np.searchsorted(self.splits[1:-1], v, side="right"))
        out[min(idx, k - 1)] = 1.0
        return out

    def config(self):
        return {"splits": self.splits, "track_nulls": self.track_nulls,
                "track_invalid": self.track_invalid, "labels": self.labels}


# ---------------------------------------------------------------------------
# Decision-tree split search (single feature vs label)
# ---------------------------------------------------------------------------

def _impurity(counts: np.ndarray, is_regression: bool, sum_y=0.0, sum_y2=0.0,
              n=0.0) -> float:
    if is_regression:
        if n <= 0:
            return 0.0
        return max(sum_y2 / n - (sum_y / n) ** 2, 0.0)
    tot = counts.sum()
    if tot <= 0:
        return 0.0
    p = counts / tot
    return float(1.0 - np.sum(p * p))  # gini


def find_tree_splits(x: np.ndarray, y: np.ndarray, *, max_depth: int = 2,
                     max_bins: int = 32, min_info_gain: float = 0.01,
                     min_instances_per_node: int = 1,
                     is_regression: Optional[bool] = None) -> list[float]:
    """Greedy single-feature decision-tree thresholds against the label.

    Mirrors reference ``DecisionTreeNumericBucketizer.computeSplits`` (which
    delegates to a Spark DecisionTree on the one feature): candidate
    thresholds from quantiles (max_bins), recursive best-gini/variance-gain
    splits, pruned by min_info_gain and min_instances_per_node. Returns the
    sorted distinct thresholds (empty -> the feature should not be split).
    """
    if is_regression is None:
        uniq = np.unique(y)
        is_regression = uniq.size > 10 or not np.allclose(uniq, np.round(uniq))
    classes = None if is_regression else np.unique(y)

    cands = np.unique(np.quantile(x, np.linspace(0, 1, max_bins + 1)[1:-1])
                      ) if x.size else np.array([])
    out: list[float] = []

    def impurity_of(idx) -> float:
        if is_regression:
            yy = y[idx]
            return _impurity(np.array([]), True, yy.sum(),
                             (yy ** 2).sum(), yy.size)
        cnt = np.array([(y[idx] == c).sum() for c in classes], np.float64)
        return _impurity(cnt, False)

    def recurse(idx: np.ndarray, depth: int):
        if depth >= max_depth or idx.size < 2 * min_instances_per_node:
            return
        parent_imp = impurity_of(idx)
        best_gain, best_t = 0.0, None
        xv = x[idx]
        for t in cands:
            left = xv <= t
            nl, nr = int(left.sum()), int((~left).sum())
            if nl < min_instances_per_node or nr < min_instances_per_node:
                continue
            gain = parent_imp - (
                nl / idx.size * impurity_of(idx[left])
                + nr / idx.size * impurity_of(idx[~left]))
            if gain > best_gain:
                best_gain, best_t = gain, float(t)
        if best_t is None or best_gain < min_info_gain:
            return
        out.append(best_t)
        left = x[idx] <= best_t
        recurse(idx[left], depth + 1)
        recurse(idx[~left], depth + 1)

    if x.size:
        recurse(np.arange(x.size), 0)
    return sorted(set(out))


class DecisionTreeNumericBucketizer(Estimator, AllowLabelAsInput):
    """Label-aware bucketizer: (label RealNN, numeric) -> bucket block.

    Fits a single-feature decision tree against the label; its thresholds
    (padded with -inf/inf) become the splits. If the tree finds no
    informative split the model emits only the null-indicator column
    (reference ``shouldSplit=false`` behavior).
    """

    # reference generic is N <: OPNumeric (DecisionTreeNumericBucketizer
    # .scala:46): Integral/Currency/Percent bucketize like Real
    in_types = (ft.RealNN, ft.OPNumeric)
    out_type = ft.OPVector

    def __init__(self, max_depth: int = 2, max_bins: int = 32,
                 min_info_gain: float = 0.01,
                 min_instances_per_node: int = 1,
                 track_nulls: bool = True, track_invalid: bool = False,
                 uid: Optional[str] = None):
        self.max_depth = max_depth
        self.max_bins = max_bins
        self.min_info_gain = min_info_gain
        self.min_instances_per_node = min_instances_per_node
        self.track_nulls = track_nulls
        self.track_invalid = track_invalid
        super().__init__(uid=uid)

    def compute_splits(self, x: np.ndarray, y: np.ndarray) -> list[float]:
        thresholds = find_tree_splits(
            x, y, max_depth=self.max_depth, max_bins=self.max_bins,
            min_info_gain=self.min_info_gain,
            min_instances_per_node=self.min_instances_per_node)
        if not thresholds:
            return []
        return [float("-inf")] + thresholds + [float("inf")]

    def fit_model(self, data):
        label_name, feat_name = self.input_names
        ycol, xcol = data.host_col(label_name), data.host_col(feat_name)
        present = xcol.mask & ycol.mask
        splits = self.compute_splits(
            np.asarray(xcol.values, np.float64)[present],
            np.asarray(ycol.values, np.float64)[present])
        return _TreeBucketizerModel(
            splits=splits, track_nulls=self.track_nulls,
            track_invalid=self.track_invalid)


class _TreeBucketizerModel(DeviceTransformer):
    """Fitted tree bucketizer; consumes only the numeric input at score."""

    in_types = (ft.RealNN, ft.OPNumeric)  # mirror the estimator's bound
    out_type = ft.OPVector

    def __init__(self, splits: Sequence[float] = (), track_nulls: bool = True,
                 track_invalid: bool = False, uid: Optional[str] = None):
        self.splits = [float(s) for s in splits]
        self.track_nulls = track_nulls
        self.track_invalid = track_invalid
        super().__init__(uid=uid)

    @property
    def should_split(self) -> bool:
        return len(self.splits) >= 2

    def runtime_input_names(self):
        return (self.input_names[1],)

    def _meta(self) -> VectorMetadata:
        feat = self.input_features[1]
        name = self.get_output().name
        if self.should_split:
            cols = _bucket_meta(name, feat, bucket_labels(self.splits),
                                self.track_invalid, self.track_nulls)
        else:
            cols = _bucket_meta(name, feat, [], False, self.track_nulls)
        return VectorMetadata(name, tuple(cols)).reindexed(0)

    def device_apply(self, params, col: fr.NumericColumn) -> fr.VectorColumn:
        if self.should_split:
            block = _bucketize_block(
                col.values, col.mask, np.asarray(self.splits, np.float64),
                self.track_invalid, self.track_nulls)
        elif self.track_nulls:
            block = (1.0 - col.mask)[:, None]
        else:
            block = jnp.zeros((col.values.shape[0], 0), jnp.float32)
        return fr.VectorColumn(block, self._meta())

    def transform_row(self, *values):
        value = values[-1]  # score-time callers may omit the label
        if self.should_split:
            helper = NumericBucketizer(
                splits=self.splits, track_nulls=self.track_nulls,
                track_invalid=self.track_invalid)
            return helper.transform_row(value)
        if self.track_nulls:
            return np.asarray([1.0 if value is None else 0.0], np.float32)
        return np.zeros(0, np.float32)

    def fitted_state(self):
        return {"splits": np.asarray(self.splits, np.float64)}

    def set_fitted_state(self, state):
        self.splits = [float(s) for s in state["splits"]]

    def config(self):
        return {"track_nulls": self.track_nulls,
                "track_invalid": self.track_invalid}


class DecisionTreeNumericMapBucketizer(Estimator, AllowLabelAsInput):
    """Per-key tree bucketizer over a RealMap (label, map) -> bucket blocks.

    Parity: reference ``DecisionTreeNumericMapBucketizer.scala`` — every map
    key gets its own tree-driven splits; keys that should not split
    contribute only their null-indicator column. ``clean_keys`` lowercases /
    strips key names the way map vectorizers do.
    """

    # any numeric map (reference M <: OPMap[N], N <: OPNumeric)
    in_types = (ft.RealNN, ft.OPMap)
    out_type = ft.OPVector

    def __init__(self, max_depth: int = 2, max_bins: int = 32,
                 min_info_gain: float = 0.01,
                 min_instances_per_node: int = 1,
                 track_nulls: bool = True, track_invalid: bool = False,
                 allow_keys: Sequence[str] = (),
                 uid: Optional[str] = None):
        self.max_depth = max_depth
        self.max_bins = max_bins
        self.min_info_gain = min_info_gain
        self.min_instances_per_node = min_instances_per_node
        self.track_nulls = track_nulls
        self.track_invalid = track_invalid
        self.allow_keys = list(allow_keys)
        super().__init__(uid=uid)

    def fit_model(self, data):
        label_name, map_name = self.input_names
        ycol, mcol = data.host_col(label_name), data.host_col(map_name)
        keys: list[str] = []
        for i in range(len(mcol)):
            d = mcol.python_value(i)
            if d:
                for k in d:
                    if k not in keys and (not self.allow_keys
                                          or k in self.allow_keys):
                        keys.append(k)
        keys.sort()
        y_all = np.asarray(ycol.values, np.float64)
        splits_per_key: dict[str, list[float]] = {}
        helper = DecisionTreeNumericBucketizer(
            max_depth=self.max_depth, max_bins=self.max_bins,
            min_info_gain=self.min_info_gain,
            min_instances_per_node=self.min_instances_per_node)
        for k in keys:
            xs, ys = [], []
            for i in range(len(mcol)):
                d = mcol.python_value(i)
                if d and k in d and ycol.mask[i]:
                    try:
                        xs.append(float(d[k]))
                    except (TypeError, ValueError):
                        # in_types is the loose OPMap bound (no common
                        # numeric-map base); enforce N <: OPNumeric here
                        raise TypeError(
                            f"{self}: expects a numeric map (reference "
                            f"OPMap[N <: OPNumeric]); key {k!r} holds "
                            f"non-numeric value {d[k]!r}") from None
                    ys.append(y_all[i])
            splits_per_key[k] = helper.compute_splits(
                np.asarray(xs, np.float64), np.asarray(ys, np.float64))
        return _TreeMapBucketizerModel(
            keys=keys, splits_per_key=splits_per_key,
            track_nulls=self.track_nulls, track_invalid=self.track_invalid)


class _TreeMapBucketizerModel(HostTransformer):
    # any numeric map (reference M <: OPMap[N], N <: OPNumeric)
    in_types = (ft.RealNN, ft.OPMap)
    out_type = ft.OPVector

    def __init__(self, keys: Sequence[str] = (),
                 splits_per_key: Optional[dict] = None,
                 track_nulls: bool = True, track_invalid: bool = False,
                 uid: Optional[str] = None):
        self.keys = list(keys)
        self.splits_per_key = {k: [float(s) for s in v]
                               for k, v in (splits_per_key or {}).items()}
        self.track_nulls = track_nulls
        self.track_invalid = track_invalid
        super().__init__(uid=uid)

    def runtime_input_names(self):
        return (self.input_names[1],)

    def _key_width(self, k: str) -> int:
        splits = self.splits_per_key.get(k, [])
        if len(splits) >= 2:
            return (len(splits) - 1 + int(self.track_invalid)
                    + int(self.track_nulls))
        return int(self.track_nulls)

    def transform_row(self, *values):
        d = values[-1] or {}
        out: list[np.ndarray] = []
        for k in self.keys:
            splits = self.splits_per_key.get(k, [])
            v = d.get(k)
            if len(splits) >= 2:
                helper = NumericBucketizer(
                    splits=splits, track_nulls=self.track_nulls,
                    track_invalid=self.track_invalid)
                out.append(helper.transform_row(v))
            elif self.track_nulls:
                out.append(np.asarray([1.0 if v is None else 0.0], np.float32))
        return (np.concatenate(out) if out
                else np.zeros(0, np.float32))

    def _meta(self) -> VectorMetadata:
        feat = self.input_features[1]
        name = self.get_output().name
        cols: list[VectorColumnMetadata] = []
        for k in self.keys:
            splits = self.splits_per_key.get(k, [])
            if len(splits) >= 2:
                cols += _bucket_meta(name, feat, bucket_labels(splits),
                                     self.track_invalid, self.track_nulls,
                                     grouping=k)
            else:
                cols += _bucket_meta(name, feat, [], False, self.track_nulls,
                                     grouping=k)
        return VectorMetadata(name, tuple(cols)).reindexed(0)

    def host_apply(self, *cols):
        mcol = cols[-1]
        rows = [self.transform_row(mcol.python_value(i))
                for i in range(len(mcol))]
        arr = (np.stack(rows) if rows
               else np.zeros((0, sum(self._key_width(k) for k in self.keys)),
                             np.float32))
        return fr.HostColumn(ft.OPVector, arr.astype(np.float32),
                             meta=self._meta())

    def output_column(self, data):
        return self.host_apply(*[data.host_col(n)
                                 for n in self.runtime_input_names()])

    def fitted_state(self):
        return {"keys": list(self.keys),  # strings ride the JSON side
                "splits": {k: self.splits_per_key[k] for k in self.keys}}

    def set_fitted_state(self, state):
        self.keys = [str(k) for k in state["keys"]]
        self.splits_per_key = {
            k: [float(s) for s in v] for k, v in state["splits"].items()}

    def config(self):
        return {"track_nulls": self.track_nulls,
                "track_invalid": self.track_invalid}


# ---------------------------------------------------------------------------
# Percentile calibrator
# ---------------------------------------------------------------------------

class PercentileCalibrator(Estimator):
    """Maps a numeric feature onto its empirical percentile in [0, buckets-1].

    Parity: reference ``PercentileCalibrator.scala`` — quantile-discretize
    into ``expected_num_buckets`` then scale bucket index onto [0, 99].
    """

    in_types = (ft.Real,)
    out_type = ft.RealNN

    def __init__(self, expected_num_buckets: int = 100,
                 uid: Optional[str] = None):
        self.expected_num_buckets = expected_num_buckets
        super().__init__(uid=uid)

    def fit_model(self, data):
        col = data.host_col(self.input_names[0])
        present = np.asarray(col.values, np.float64)[col.mask]
        if present.size:
            qs = np.linspace(0, 1, self.expected_num_buckets + 1)[1:-1]
            edges = np.unique(np.quantile(present, qs))
        else:
            edges = np.array([], np.float64)
        return _PercentileModel(splits=[float(e) for e in edges],
                                buckets=self.expected_num_buckets)


class _PercentileModel(DeviceTransformer):
    in_types = (ft.Real,)
    out_type = ft.RealNN

    def __init__(self, splits: Sequence[float] = (), buckets: int = 100,
                 uid: Optional[str] = None):
        self.splits = [float(s) for s in splits]
        self.buckets = buckets
        super().__init__(uid=uid)

    def _scale(self, idx):
        # actual bucket count may be < requested when quantiles collapse;
        # rescale onto [0, 99] like the reference's outputCol * 99/maxBucket
        n_buckets = max(len(self.splits) + 1, 1)
        return jnp.round(idx * (99.0 / max(n_buckets - 1, 1)))

    def device_params(self):
        return jnp.asarray(self.splits, jnp.float32)

    def device_apply(self, params, col: fr.NumericColumn) -> fr.NumericColumn:
        if len(self.splits) == 0:
            return fr.NumericColumn(jnp.zeros_like(col.values),
                                    jnp.ones_like(col.mask))
        idx = jnp.searchsorted(params, col.values, side="right")
        scaled = self._scale(idx.astype(jnp.float32))
        return fr.NumericColumn(scaled * col.mask,
                                jnp.ones_like(col.mask))

    def transform_row(self, value):
        if value is None or len(self.splits) == 0:
            return 0.0
        idx = float(np.searchsorted(self.splits, float(value), side="right"))
        return float(np.asarray(self._scale(idx)))

    def fitted_state(self):
        return {"splits": np.asarray(self.splits, np.float64)}

    def set_fitted_state(self, state):
        self.splits = [float(s) for s in state["splits"]]

    def config(self):
        return {"buckets": self.buckets}
