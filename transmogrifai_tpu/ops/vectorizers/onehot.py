"""Categorical pivot vectorizers: topK one-hot + OTHER + null indicator.

Parity: reference ``core/.../stages/impl/feature/OpOneHotVectorizer.scala``
(``OpSetVectorizer`` for MultiPickList): per input feature, learn the topK
category values by count (>= min_support), emit one column per category plus
an OTHER column (unseen/rare values) and a null-indicator column.

TPU-first: categories are learned as label strings (vocabulary-independent);
at transform time the device program builds a static code->slot gather table
from the input ``CodesColumn``'s dictionary (aux data, so a new scoring
vocabulary retraces once and is cached) and the pivot is a one-hot gather —
MXU-friendly and fused into the layer program.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from transmogrifai_tpu import frame as fr
from transmogrifai_tpu.stages.base import DeviceTransformer, Estimator, HostTransformer
from transmogrifai_tpu.types import feature_types as ft
from transmogrifai_tpu.vector_metadata import (
    parent_of,
    NULL_INDICATOR, OTHER, VectorColumnMetadata, VectorMetadata,
)

__all__ = ["OneHotVectorizer", "OneHotModel", "SetVectorizer", "SetModel"]


def _pivot_meta(out_name: str, input_feats, categories: Sequence[Sequence[str]],
                track_nulls: bool) -> VectorMetadata:
    cols = []
    for f, cats in zip(input_feats, categories):
        for c in cats:
            cols.append(VectorColumnMetadata(
                *parent_of(f), grouping=f.name,
                indicator_value=c))
        cols.append(VectorColumnMetadata(
            *parent_of(f), grouping=f.name,
            indicator_value=OTHER))
        if track_nulls:
            cols.append(VectorColumnMetadata(
                *parent_of(f), grouping=f.name,
                indicator_value=NULL_INDICATOR))
    return VectorMetadata(out_name, tuple(cols)).reindexed(0)


def _top_k(values: Sequence[str], counts: Sequence[int], top_k: int,
           min_support: int) -> list[str]:
    """Most frequent first; ties lexicographic; support threshold applied."""
    pairs = [(c, v) for v, c in zip(values, counts) if c >= min_support]
    pairs.sort(key=lambda cv: (-cv[0], cv[1]))
    return [v for _, v in pairs[:top_k]]


class OneHotVectorizer(Estimator):
    """Variadic estimator over text-ish categorical inputs."""

    variadic = True
    in_types = (ft.Text,)
    out_type = ft.OPVector

    def __init__(self, top_k: int = 20, min_support: int = 10,
                 track_nulls: bool = True,
                 max_pct_cardinality: float = 1.0,
                 uid: Optional[str] = None):
        self.top_k = top_k
        self.min_support = min_support
        self.track_nulls = track_nulls
        self.max_pct_cardinality = max_pct_cardinality
        super().__init__(uid=uid)

    def fit_model(self, data):
        categories: list[list[str]] = []
        n = max(data.n_rows, 1)
        for name in self.input_names:
            codes_col = data.device_col(name)
            codes = np.asarray(codes_col.codes)
            vocab = codes_col.vocab
            counts = np.bincount(codes[codes >= 0], minlength=len(vocab))
            if len(vocab) / n > self.max_pct_cardinality:
                categories.append([])  # too-high cardinality: pivot nothing
            else:
                categories.append(
                    _top_k(list(vocab), counts.tolist(), self.top_k,
                           self.min_support))
        return OneHotModel(categories=categories, track_nulls=self.track_nulls)


class OneHotModel(DeviceTransformer):
    variadic = True
    in_types = (ft.Text,)
    out_type = ft.OPVector

    def __init__(self, categories: Sequence[Sequence[str]] = (),
                 track_nulls: bool = True, uid: Optional[str] = None):
        self.categories = [list(c) for c in categories]
        self.track_nulls = track_nulls
        super().__init__(uid=uid)

    def device_apply(self, params, *cols: fr.CodesColumn) -> fr.VectorColumn:
        pieces = []
        for i, c in enumerate(cols):
            cats = self.categories[i]
            slot_of = {v: j for j, v in enumerate(cats)}
            k = len(cats)
            width = k + 2 if self.track_nulls else k + 1
            # static gather table from this column's dictionary (aux data)
            table = np.full(max(len(c.vocab), 1), k, dtype=np.int32)  # -> OTHER
            for j, v in enumerate(c.vocab):
                table[j] = slot_of.get(v, k)
            null_slot = k + 1 if self.track_nulls else width  # width -> zeros
            slots = jnp.where(c.codes >= 0,
                              jnp.asarray(table)[jnp.clip(c.codes, 0)],
                              null_slot)
            pieces.append(jax.nn.one_hot(slots, width, dtype=jnp.float32))
        meta = _pivot_meta(self.get_output().name, self.input_features,
                           self.categories, self.track_nulls)
        return fr.VectorColumn(jnp.concatenate(pieces, axis=1), meta)

    def transform_row(self, *values):
        out = []
        for i, v in enumerate(values):
            cats = self.categories[i]
            k = len(cats)
            width = k + 2 if self.track_nulls else k + 1
            row = [0.0] * width
            if v is None:
                if self.track_nulls:
                    row[k + 1] = 1.0
            elif v in cats:
                row[cats.index(v)] = 1.0
            else:
                row[k] = 1.0
            out.extend(row)
        return np.asarray(out, dtype=np.float32)

    def fitted_state(self):
        return {"categories": self.categories}

    def set_fitted_state(self, state):
        self.categories = [list(c) for c in state["categories"]]


class SetVectorizer(Estimator):
    """MultiPickList pivot: topK multi-hot + OTHER + null."""

    variadic = True
    in_types = (ft.MultiPickList,)
    out_type = ft.OPVector

    def __init__(self, top_k: int = 20, min_support: int = 10,
                 track_nulls: bool = True, uid: Optional[str] = None):
        self.top_k = top_k
        self.min_support = min_support
        self.track_nulls = track_nulls
        super().__init__(uid=uid)

    def fit_model(self, data):
        categories = []
        for name in self.input_names:
            col = data.host_col(name)
            counts: dict[str, int] = {}
            for s in col.values:
                for v in (s or ()):
                    counts[v] = counts.get(v, 0) + 1
            categories.append(_top_k(list(counts), list(counts.values()),
                                     self.top_k, self.min_support))
        return SetModel(categories=categories, track_nulls=self.track_nulls)


class SetModel(HostTransformer):
    variadic = True
    in_types = (ft.MultiPickList,)
    out_type = ft.OPVector

    def __init__(self, categories: Sequence[Sequence[str]] = (),
                 track_nulls: bool = True, uid: Optional[str] = None):
        self.categories = [list(c) for c in categories]
        self.track_nulls = track_nulls
        super().__init__(uid=uid)

    def transform_row(self, *values):
        out = []
        for i, s in enumerate(values):
            cats = self.categories[i]
            k = len(cats)
            width = k + 2 if self.track_nulls else k + 1
            row = [0.0] * width
            if not s:
                if self.track_nulls:
                    row[k + 1] = 1.0
            else:
                for v in s:
                    if v in cats:
                        row[cats.index(v)] = 1.0
                    else:
                        row[k] = 1.0
            out.extend(row)
        return np.asarray(out, dtype=np.float32)

    def host_apply(self, *cols: fr.HostColumn) -> fr.HostColumn:
        n = len(cols[0])
        rows = [self.transform_row(*(c.values[i] for c in cols))
                for i in range(n)]
        meta = _pivot_meta(self.get_output().name, self.input_features,
                           self.categories, self.track_nulls)
        return fr.HostColumn(ft.OPVector, np.stack(rows), meta=meta)

    def fitted_state(self):
        return {"categories": self.categories}

    def set_fitted_state(self, state):
        self.categories = [list(c) for c in state["categories"]]
