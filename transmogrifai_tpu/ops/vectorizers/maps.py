"""Map vectorizers: key-expanded vectorization of map features.

Parity: reference ``core/.../stages/impl/feature/{OPMapVectorizer,
TextMapPivotVectorizer, MultiPickListMapVectorizer, DateMapToUnitCircleVectorizer,
GeolocationMapVectorizer}.scala`` and ``SmartTextMapVectorizer.scala`` — maps
expand to one column block per key seen at fit time (sorted key order),
then each key's block follows its scalar vectorizer's semantics (mean-fill
numeric, topK pivot, multi-hot, sin/cos, midpoint-fill geo), with
``grouping = key`` provenance metadata throughout (whitelist/blacklist key
filtering like the reference's map params).
"""

from __future__ import annotations

import numpy as np

from typing import Optional, Sequence

from transmogrifai_tpu import frame as fr
from transmogrifai_tpu.ops.smart_text import TextStats
from transmogrifai_tpu.ops.vectorizers.dates import TIME_PERIODS
from transmogrifai_tpu.ops.vectorizers.hashing import hash_token, tokenize
from transmogrifai_tpu.ops.vectorizers.onehot import _top_k
from transmogrifai_tpu.stages.base import Estimator, HostTransformer
from transmogrifai_tpu.types import feature_types as ft
from transmogrifai_tpu.vector_metadata import (
    NULL_INDICATOR, OTHER, VectorColumnMetadata, VectorMetadata, parent_of,
)

__all__ = [
    "FilterMapKeys", "Base64MapMimeDetector",
    "RealMapVectorizer", "IntegralMapVectorizer", "BinaryMapVectorizer",
    "TextMapPivotVectorizer", "MultiPickListMapVectorizer",
    "DateMapToUnitCircleVectorizer", "GeolocationMapVectorizer",
    "SmartTextMapVectorizer", "TextMapLenEstimator", "TextMapNullEstimator",
]


class _MapVectorizerBase(Estimator):
    """Shared fit plumbing: collect keys (+ per-key state) per input."""

    variadic = True
    out_type = ft.OPVector

    def __init__(self, allow_keys: Sequence[str] = (),
                 block_keys: Sequence[str] = (),
                 block_keys_by_feature: Optional[dict] = None,
                 track_nulls: bool = True, uid: Optional[str] = None,
                 **extra):
        self.allow_keys = tuple(allow_keys)
        self.block_keys = tuple(block_keys)
        #: per-feature key exclusions (reference RawFeatureFilter's per-key
        #: map blocklist, applied by OpWorkflow.setBlocklist — here the
        #: workflow rewires fitted map vectorizers with this dict)
        self.block_keys_by_feature = {
            str(n): tuple(ks)
            for n, ks in (block_keys_by_feature or {}).items()}
        #: WORKFLOW-applied per-key exclusions (RawFeatureFilter results,
        #: set by Workflow._apply_map_key_blocklist) — kept separate from
        #: the user-owned ``block_keys_by_feature`` so each train() can
        #: replace its own exclusions without ever touching user config
        self.wf_block_keys_by_feature: dict = {}
        self.track_nulls = track_nulls
        for k, v in extra.items():
            setattr(self, k, v)
        super().__init__(uid=uid)

    def _keep_key(self, k: str, feature: Optional[str] = None) -> bool:
        if self.allow_keys and k not in self.allow_keys:
            return False
        if feature is not None \
                and (k in self.block_keys_by_feature.get(feature, ())
                     or k in self.wf_block_keys_by_feature.get(feature, ())):
            return False
        return k not in self.block_keys

    def _collect(self, col: fr.HostColumn, feature: Optional[str] = None):
        """-> {key: [values...]} (missing key -> absent)."""
        per_key: dict[str, list] = {}
        for m in col.values:
            for k, v in (m or {}).items():
                if self._keep_key(k, feature):
                    per_key.setdefault(k, []).append(v)
        return per_key


class _KeyedModelBase(HostTransformer):
    """Shared transform plumbing: iterate (input, key) blocks."""

    variadic = True
    out_type = ft.OPVector

    def __init__(self, keys: Sequence[Sequence[str]] = (),
                 track_nulls: bool = True, uid: Optional[str] = None,
                 **extra):
        self.keys = [list(k) for k in keys]
        self.track_nulls = track_nulls
        for k, v in extra.items():
            setattr(self, k, v)
        super().__init__(uid=uid)

    # subclass: width per key block, fill one key block, metadata per key
    def key_width(self, i: int, key: str) -> int:
        raise NotImplementedError

    def fill_key(self, out: np.ndarray, off: int, i: int, key: str, value):
        raise NotImplementedError

    def key_meta(self, i: int, key: str, parent) -> list:
        raise NotImplementedError

    def _total_width(self) -> int:
        return sum(self.key_width(i, k)
                   for i, ks in enumerate(self.keys) for k in ks)

    def transform_row(self, *values):
        out = np.zeros(self._total_width(), dtype=np.float32)
        off = 0
        for i, ks in enumerate(self.keys):
            m = values[i] or {}
            for k in ks:
                self.fill_key(out, off, i, k, m.get(k))
                off += self.key_width(i, k)
        return out

    def fill_key_column(self, out: np.ndarray, off: int, i: int, key: str,
                        values: list) -> None:
        """Columnar fill for one (feature, key) block over ALL rows.

        Default: the per-row ``fill_key`` loop. Hot subclasses (numeric,
        pivot) override with vectorized fills — wide keyed maps are the
        reference's OPMapVectorizer scale problem, and per-(row, key)
        Python method dispatch dominates otherwise."""
        for r, v in enumerate(values):
            self.fill_key(out[r], off, i, key, v)

    def host_apply(self, *cols: fr.HostColumn) -> fr.HostColumn:
        n = len(cols[0])
        out = np.zeros((n, self._total_width()), dtype=np.float32)
        off = 0
        for i, ks in enumerate(self.keys):
            vals = cols[i].values
            for k in ks:
                vk = [m.get(k) if m else None for m in vals]
                self.fill_key_column(out, off, i, k, vk)
                off += self.key_width(i, k)
        return fr.HostColumn(ft.OPVector, out, meta=self._meta())

    def _meta(self) -> VectorMetadata:
        cols = []
        for i, ks in enumerate(self.keys):
            f = self.input_features[i]
            parent = parent_of(f)
            for k in ks:
                cols.extend(self.key_meta(i, k, parent))
        return VectorMetadata(self.get_output().name, tuple(cols)).reindexed(0)

    def fitted_state(self):
        return {"keys": self.keys, **self._extra_state()}

    def _extra_state(self):
        return {}

    def set_fitted_state(self, state):
        self.keys = [list(k) for k in state["keys"]]
        for k, v in state.items():
            if k != "keys":
                setattr(self, k, v)


# ---------------------------------------------------------------------------
# numeric maps (Real/Currency/Percent/Integral/Binary)
# ---------------------------------------------------------------------------

class _NumericMapModel(_KeyedModelBase):
    in_types = (ft.OPMap,)

    def key_width(self, i, key):
        return 2 if self.track_nulls else 1

    def fill_key(self, out, off, i, key, value):
        fill = self.fills[i].get(key, 0.0)
        missing = value is None
        out[off] = fill if missing else float(value)
        if self.track_nulls:
            out[off + 1] = 1.0 if missing else 0.0

    def fill_key_column(self, out, off, i, key, values):
        fill = float(self.fills[i].get(key, 0.0))
        n = len(values)
        out[:, off] = np.fromiter(
            (fill if v is None else float(v) for v in values),
            np.float32, count=n)
        if self.track_nulls:
            out[:, off + 1] = np.fromiter(
                (1.0 if v is None else 0.0 for v in values),
                np.float32, count=n)

    def key_meta(self, i, key, parent):
        cols = [VectorColumnMetadata(*parent, grouping=key)]
        if self.track_nulls:
            cols.append(VectorColumnMetadata(
                *parent, grouping=key, indicator_value=NULL_INDICATOR))
        return cols

    def _extra_state(self):
        return {"fills": self.fills}


class RealMapVectorizer(_MapVectorizerBase):
    """RealMap/CurrencyMap/PercentMap: per-key mean fill + null tracking."""

    in_types = (ft.RealMap,)

    def fit_model(self, data):
        keys, fills = [], []
        for name in self.input_names:
            per_key = self._collect(data.host_col(name), name)
            ks = sorted(per_key)
            keys.append(ks)
            fills.append({k: float(np.mean([float(v) for v in per_key[k]]))
                          for k in ks})
        return _NumericMapModel(keys=keys, track_nulls=self.track_nulls,
                                fills=fills)


class IntegralMapVectorizer(_MapVectorizerBase):
    """IntegralMap: per-key mode fill."""

    in_types = (ft.IntegralMap,)

    def fit_model(self, data):
        keys, fills = [], []
        for name in self.input_names:
            per_key = self._collect(data.host_col(name), name)
            ks = sorted(per_key)
            keys.append(ks)
            f = {}
            for k in ks:
                vals, cnts = np.unique([int(v) for v in per_key[k]],
                                       return_counts=True)
                f[k] = float(vals[np.argmax(cnts)])
            fills.append(f)
        return _NumericMapModel(keys=keys, track_nulls=self.track_nulls,
                                fills=fills)


class BinaryMapVectorizer(_MapVectorizerBase):
    """BinaryMap: false-fill + null tracking."""

    in_types = (ft.BinaryMap,)

    def fit_model(self, data):
        keys = [sorted(self._collect(data.host_col(n), n))
                for n in self.input_names]
        fills = [{k: 0.0 for k in ks} for ks in keys]
        return _NumericMapModel(keys=keys, track_nulls=self.track_nulls,
                                fills=fills)


# ---------------------------------------------------------------------------
# categorical maps
# ---------------------------------------------------------------------------

class _PivotMapModel(_KeyedModelBase):
    in_types = (ft.TextMap,)

    def key_width(self, i, key):
        k = len(self.categories[i][key])
        return k + 1 + (1 if self.track_nulls else 0)

    def fill_key(self, out, off, i, key, value):
        cats = self.categories[i][key]
        k = len(cats)
        if value is None:
            if self.track_nulls:
                out[off + k + 1] = 1.0
        elif value in cats:
            out[off + cats.index(value)] = 1.0
        else:
            out[off + k] = 1.0

    def fill_key_column(self, out, off, i, key, values):
        from transmogrifai_tpu.ops.smart_text import pivot_slot_fill
        from transmogrifai_tpu.utils.dict_encode import (
            dict_encode, scan_column,
        )
        vals = np.asarray(values, dtype=object)
        null_mask, all_str = scan_column(vals)
        if not all_str:  # non-string values: exact per-row matching
            for r, v in enumerate(values):
                self.fill_key(out[r], off, i, key, v)
            return
        codes, vocab = dict_encode(vals)
        pivot_slot_fill(out, off, self.categories[i][key], codes, vocab,
                        null_mask, self.track_nulls)

    def key_meta(self, i, key, parent):
        cols = [VectorColumnMetadata(*parent, grouping=key, indicator_value=c)
                for c in self.categories[i][key]]
        cols.append(VectorColumnMetadata(*parent, grouping=key,
                                         indicator_value=OTHER))
        if self.track_nulls:
            cols.append(VectorColumnMetadata(
                *parent, grouping=key, indicator_value=NULL_INDICATOR))
        return cols

    def _extra_state(self):
        return {"categories": self.categories}


class TextMapPivotVectorizer(_MapVectorizerBase):
    """TextMap-family: topK pivot per key."""

    in_types = (ft.TextMap,)

    def __init__(self, top_k: int = 20, min_support: int = 10, **kw):
        super().__init__(top_k=top_k, min_support=min_support, **kw)

    def fit_model(self, data):
        keys, categories = [], []
        for name in self.input_names:
            per_key = self._collect(data.host_col(name), name)
            ks = sorted(per_key)
            keys.append(ks)
            cat = {}
            for k in ks:
                counts: dict[str, int] = {}
                for v in per_key[k]:
                    counts[v] = counts.get(v, 0) + 1
                cat[k] = _top_k(list(counts), list(counts.values()),
                                self.top_k, self.min_support)
            categories.append(cat)
        return _PivotMapModel(keys=keys, track_nulls=self.track_nulls,
                              categories=categories)


class _MultiPickMapModel(_PivotMapModel):
    in_types = (ft.MultiPickListMap,)

    def fill_key_column(self, out, off, i, key, values):
        # values are SETS/LISTS of picks, not scalars: the inherited pivot
        # fast path would treat a string value as one category (and ''
        # as a category instead of empty) — keep the exact per-row fill
        for r, v in enumerate(values):
            self.fill_key(out[r], off, i, key, v)

    def fill_key(self, out, off, i, key, value):
        cats = self.categories[i][key]
        k = len(cats)
        if not value:
            if self.track_nulls:
                out[off + k + 1] = 1.0
            return
        for v in value:
            if v in cats:
                out[off + cats.index(v)] = 1.0
            else:
                out[off + k] = 1.0


class MultiPickListMapVectorizer(_MapVectorizerBase):
    in_types = (ft.MultiPickListMap,)

    def __init__(self, top_k: int = 20, min_support: int = 10, **kw):
        super().__init__(top_k=top_k, min_support=min_support, **kw)

    def fit_model(self, data):
        keys, categories = [], []
        for name in self.input_names:
            per_key = self._collect(data.host_col(name), name)
            ks = sorted(per_key)
            keys.append(ks)
            cat = {}
            for k in ks:
                counts: dict[str, int] = {}
                for s in per_key[k]:
                    for v in (s or ()):
                        counts[v] = counts.get(v, 0) + 1
                cat[k] = _top_k(list(counts), list(counts.values()),
                                self.top_k, self.min_support)
            categories.append(cat)
        return _MultiPickMapModel(keys=keys, track_nulls=self.track_nulls,
                                  categories=categories)


# ---------------------------------------------------------------------------
# date / geolocation maps
# ---------------------------------------------------------------------------

class _DateMapModel(_KeyedModelBase):
    in_types = (ft.DateMap,)

    def key_width(self, i, key):
        return 2 + (1 if self.track_nulls else 0)

    def fill_key(self, out, off, i, key, value):
        if value is None:
            if self.track_nulls:
                out[off + 2] = 1.0
            return
        modulus, offset = TIME_PERIODS[self.time_period]
        theta = ((float(value) + offset) % modulus) / modulus * 2 * np.pi
        out[off] = np.sin(theta)
        out[off + 1] = np.cos(theta)

    def key_meta(self, i, key, parent):
        cols = [VectorColumnMetadata(*parent, grouping=key,
                                     descriptor_value=f"sin_{self.time_period}"),
                VectorColumnMetadata(*parent, grouping=key,
                                     descriptor_value=f"cos_{self.time_period}")]
        if self.track_nulls:
            cols.append(VectorColumnMetadata(
                *parent, grouping=key, indicator_value=NULL_INDICATOR))
        return cols

    def _extra_state(self):
        return {"time_period": self.time_period}


class DateMapToUnitCircleVectorizer(_MapVectorizerBase):
    in_types = (ft.DateMap,)

    def __init__(self, time_period: str = "HourOfDay", **kw):
        if time_period not in TIME_PERIODS:
            raise ValueError(f"Unknown time period {time_period!r}")
        super().__init__(time_period=time_period, **kw)

    def fit_model(self, data):
        keys = [sorted(self._collect(data.host_col(n), n))
                for n in self.input_names]
        return _DateMapModel(keys=keys, track_nulls=self.track_nulls,
                             time_period=self.time_period)


class _GeoMapModel(_KeyedModelBase):
    in_types = (ft.GeolocationMap,)

    def key_width(self, i, key):
        return 3 + (1 if self.track_nulls else 0)

    def fill_key(self, out, off, i, key, value):
        if not value:
            out[off:off + 3] = self.fills[i].get(key, [0.0, 0.0, 0.0])
            if self.track_nulls:
                out[off + 3] = 1.0
        else:
            out[off:off + 3] = [float(x) for x in value]

    def key_meta(self, i, key, parent):
        cols = [VectorColumnMetadata(*parent, grouping=key, descriptor_value=p)
                for p in ("lat", "lon", "accuracy")]
        if self.track_nulls:
            cols.append(VectorColumnMetadata(
                *parent, grouping=key, indicator_value=NULL_INDICATOR))
        return cols

    def _extra_state(self):
        return {"fills": self.fills}


class GeolocationMapVectorizer(_MapVectorizerBase):
    in_types = (ft.GeolocationMap,)

    def fit_model(self, data):
        keys, fills = [], []
        for name in self.input_names:
            per_key = self._collect(data.host_col(name), name)
            ks = sorted(per_key)
            keys.append(ks)
            f = {}
            for k in ks:
                pts = np.asarray([p for p in per_key[k] if p], np.float64)
                f[k] = (pts.mean(axis=0).tolist() if pts.size
                        else [0.0, 0.0, 0.0])
            fills.append(f)
        return _GeoMapModel(keys=keys, track_nulls=self.track_nulls,
                            fills=fills)


# ---------------------------------------------------------------------------
# smart text maps
# ---------------------------------------------------------------------------

class _SmartTextMapModel(_KeyedModelBase):
    in_types = (ft.TextMap,)

    def __init__(self, keys: Sequence[Sequence[str]] = (),
                 track_nulls: bool = True, uid: Optional[str] = None,
                 **extra):
        # signature mirrors _KeyedModelBase so ctor-reflecting config()
        # keeps carrying keys/track_nulls through save/load
        #: "feature.key" -> detection record for keys dropped as sensitive
        #: (SensitiveFeatureInformation analog; merged into ModelInsights)
        self.sensitive: dict = {}
        super().__init__(keys=keys, track_nulls=track_nulls, uid=uid,
                         **extra)

    def sensitive_info(self) -> dict:
        return dict(self.sensitive)

    def key_width(self, i, key):
        t = self.treatments[i][key]
        if t["kind"] == "pivot":
            return len(t["categories"]) + 1 + (1 if self.track_nulls else 0)
        return self.num_hash_features + (1 if self.track_nulls else 0)

    def fill_key(self, out, off, i, key, value):
        t = self.treatments[i][key]
        if t["kind"] == "pivot":
            cats = t["categories"]
            k = len(cats)
            if value is None:
                if self.track_nulls:
                    out[off + k + 1] = 1.0
            elif value in cats:
                out[off + cats.index(value)] = 1.0
            else:
                out[off + k] = 1.0
            return
        if value is not None:
            for tok in tokenize(value):
                out[off + hash_token(tok, self.num_hash_features)] += 1.0
        if self.track_nulls:
            out[off + self.num_hash_features] = 1.0 if value is None else 0.0

    def fill_key_column(self, out, off, i, key, values):
        """Columnar per-key fill via the SHARED SmartText helpers (pivot
        slot gather / per-unique hashed table — one implementation for the
        scalar and map paths); non-string values and over-cap hash vocabs
        fall back to the exact per-row fill."""
        from transmogrifai_tpu.ops.smart_text import (
            hashed_unique_table, pivot_slot_fill,
        )
        from transmogrifai_tpu.utils.dict_encode import (
            dict_encode, scan_column,
        )
        vals = np.asarray(values, dtype=object)
        null_mask, all_str = scan_column(vals)
        t = self.treatments[i][key]
        uvecs = None
        if all_str:
            codes, vocab = dict_encode(vals)
            if t["kind"] != "pivot":
                uvecs = hashed_unique_table(vocab, self.num_hash_features)
        if not all_str or (t["kind"] != "pivot" and uvecs is None):
            # non-strings (stringified encoding would skew matching) or an
            # over-cap hash vocab (table would not fit): exact per-row
            for r, v in enumerate(values):
                self.fill_key(out[r], off, i, key, v)
            return
        if t["kind"] == "pivot":
            pivot_slot_fill(out, off, t["categories"], codes, vocab,
                            null_mask, self.track_nulls)
            return
        rows = np.nonzero(~null_mask)[0]
        out[rows, off:off + self.num_hash_features] = uvecs[codes[rows]]
        if self.track_nulls:
            out[:, off + self.num_hash_features] = \
                null_mask.astype(np.float32)

    def key_meta(self, i, key, parent):
        t = self.treatments[i][key]
        cols = []
        if t["kind"] == "pivot":
            for c in t["categories"]:
                cols.append(VectorColumnMetadata(*parent, grouping=key,
                                                 indicator_value=c))
            cols.append(VectorColumnMetadata(*parent, grouping=key,
                                             indicator_value=OTHER))
        else:
            for j in range(self.num_hash_features):
                cols.append(VectorColumnMetadata(
                    *parent, grouping=key, descriptor_value=f"hash_{j}"))
        if self.track_nulls:
            cols.append(VectorColumnMetadata(
                *parent, grouping=key, indicator_value=NULL_INDICATOR))
        return cols

    def _extra_state(self):
        return {"treatments": self.treatments,
                "num_hash_features": self.num_hash_features,
                "sensitive": self.sensitive}


class SmartTextMapVectorizer(_MapVectorizerBase):
    """Per-key cardinality-adaptive pivot/hash (reference
    SmartTextMapVectorizer), with optional per-key name/sensitive detection
    (the map variant of the scalar SmartTextVectorizer's NameDetectFun):
    keys whose values look like human names beyond ``name_threshold`` are
    dropped from the expansion and RECORDED (``sensitive_info()`` reaches
    ModelInsights like the scalar path)."""

    in_types = (ft.TextMap,)

    def __init__(self, max_cardinality: int = 100, top_k: int = 20,
                 min_support: int = 10, num_hash_features: int = 128,
                 detect_names: bool = False, name_threshold: float = 0.5,
                 **kw):
        super().__init__(max_cardinality=max_cardinality, top_k=top_k,
                         min_support=min_support,
                         num_hash_features=num_hash_features,
                         detect_names=detect_names,
                         name_threshold=name_threshold, **kw)

    def fit_model(self, data):
        from transmogrifai_tpu.ops.smart_text import looks_like_name
        keys, treatments = [], []
        sensitive: dict[str, dict] = {}
        for name in self.input_names:
            per_key = self._collect(data.host_col(name), name)
            ks = []
            tr = {}
            for k in sorted(per_key):
                vals = per_key[k]
                if self.detect_names and vals:
                    hits = sum(1 for v in vals if looks_like_name(str(v)))
                    if hits / len(vals) >= self.name_threshold:
                        sensitive[f"{name}.{k}"] = {
                            "detected": True,
                            "probName": hits / len(vals),
                            "action": "removedFromVector"}
                        continue  # sensitive key: never expands
                ks.append(k)
                stats = TextStats(max_cardinality=self.max_cardinality)
                for v in vals:
                    stats.add(v)
                if not stats.overflowed:
                    cats = _top_k(list(stats.counts),
                                  list(stats.counts.values()),
                                  self.top_k, self.min_support)
                    tr[k] = {"kind": "pivot", "categories": cats}
                else:
                    tr[k] = {"kind": "hash"}
            keys.append(ks)
            treatments.append(tr)
        model = _SmartTextMapModel(keys=keys, track_nulls=self.track_nulls,
                                   treatments=treatments,
                                   num_hash_features=self.num_hash_features)
        model.sensitive = sensitive
        return model


# ---------------------------------------------------------------------------
# text-map length / null estimators
# ---------------------------------------------------------------------------

class _TextMapLenModel(_KeyedModelBase):
    in_types = (ft.TextMap,)

    def key_width(self, i, key):
        return 1

    def fill_key(self, out, off, i, key, value):
        out[off] = 0.0 if value is None else float(len(str(value)))

    def key_meta(self, i, key, parent):
        return [VectorColumnMetadata(*parent, grouping=key,
                                     descriptor_value="TextLen")]


class TextMapLenEstimator(_MapVectorizerBase):
    """Per-key text lengths of a TextMap -> OPVector (reference
    ``TextMapLenEstimator.scala`` — missing keys contribute length 0)."""

    in_types = (ft.TextMap,)

    def fit_model(self, data):
        keys = [sorted(self._collect(data.host_col(n), n))
                for n in self.input_names]
        return _TextMapLenModel(keys=keys, track_nulls=False)


class _TextMapNullModel(_KeyedModelBase):
    in_types = (ft.TextMap,)

    def key_width(self, i, key):
        return 1

    def fill_key(self, out, off, i, key, value):
        out[off] = 1.0 if value is None else 0.0

    def key_meta(self, i, key, parent):
        return [VectorColumnMetadata(*parent, grouping=key,
                                     indicator_value=NULL_INDICATOR)]


class TextMapNullEstimator(_MapVectorizerBase):
    """Per-key null indicators of a TextMap -> OPVector (reference
    ``TextMapNullEstimator.scala``)."""

    in_types = (ft.TextMap,)

    def fit_model(self, data):
        keys = [sorted(self._collect(data.host_col(n), n))
                for n in self.input_names]
        return _TextMapNullModel(keys=keys, track_nulls=False)


class FilterMapKeys(HostTransformer):
    """Key allow/block filtering on any map feature, type-preserving
    (reference RichMapFeature.filter, RichMapFeature.scala:58-88)."""

    in_types = (ft.OPMap,)
    out_type = ft.OPMap

    def __init__(self, allow_list: Sequence[str] = (),
                 block_list: Sequence[str] = (),
                 uid: Optional[str] = None):
        self.allow_list = list(allow_list)
        self.block_list = list(block_list)
        self._allow = frozenset(self.allow_list)
        self._block = frozenset(self.block_list)
        super().__init__(uid=uid)

    def set_input(self, *features):
        super().set_input(*features)
        self.out_type = features[0].ftype  # type-preserving
        return self

    def transform_row(self, value):
        if not value:
            return {}
        allow, block = self._allow, self._block
        return {k: v for k, v in value.items()
                if (not allow or k in allow) and k not in block}

    def config(self):
        return {"allow_list": self.allow_list,
                "block_list": self.block_list}


class Base64MapMimeDetector(HostTransformer):
    """Base64Map -> PickListMap of detected MIME types per key (reference
    RichMapFeature.detectMimeTypes)."""

    in_types = (ft.Base64Map,)
    out_type = ft.PickListMap

    def __init__(self, uid: Optional[str] = None):
        super().__init__(uid=uid)

    def transform_row(self, value):
        if not value:
            return {}
        import base64

        from transmogrifai_tpu.ops.parsers import detect_mime
        out = {}
        for k, v in value.items():
            if v is None:
                continue
            try:
                data = base64.b64decode(v, validate=False)
            except Exception:  # failure-ok: invalid base64 entry is skipped
                continue
            if data:
                out[k] = detect_mime(data)
        return out
