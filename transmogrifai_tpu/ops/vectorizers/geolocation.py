"""Geolocation vectorizer: (lat, lon, accuracy) -> numeric block.

Parity: reference ``core/.../stages/impl/feature/GeolocationVectorizer.scala``
— mean-fill missing coordinates (geolocation midpoint of the training data)
plus a null-indicator column per input.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from transmogrifai_tpu import frame as fr
from transmogrifai_tpu.stages.base import Estimator, HostTransformer
from transmogrifai_tpu.types import feature_types as ft
from transmogrifai_tpu.vector_metadata import (
    parent_of,
    NULL_INDICATOR, VectorColumnMetadata, VectorMetadata,
)

__all__ = ["GeolocationVectorizer", "GeolocationModel"]


class GeolocationVectorizer(Estimator):
    variadic = True
    in_types = (ft.Geolocation,)
    out_type = ft.OPVector

    def __init__(self, fill_with_mean: bool = True, track_nulls: bool = True,
                 uid: Optional[str] = None):
        self.fill_with_mean = fill_with_mean
        self.track_nulls = track_nulls
        super().__init__(uid=uid)

    def fit_model(self, data):
        fills = []
        for name in self.input_names:
            col = data.host_col(name)
            present = col.values[col.mask]
            if self.fill_with_mean and present.shape[0] > 0:
                fills.append(present.mean(axis=0).tolist())
            else:
                fills.append([0.0, 0.0, 0.0])
        return GeolocationModel(fill_values=fills, track_nulls=self.track_nulls)


class GeolocationModel(HostTransformer):
    variadic = True
    in_types = (ft.Geolocation,)
    out_type = ft.OPVector

    def __init__(self, fill_values: Sequence[Sequence[float]] = (),
                 track_nulls: bool = True, uid: Optional[str] = None):
        self.fill_values = [list(v) for v in fill_values]
        self.track_nulls = track_nulls
        super().__init__(uid=uid)

    def transform_row(self, *values):
        out = []
        for i, v in enumerate(values):
            missing = not v
            out.extend(self.fill_values[i] if missing else list(v))
            if self.track_nulls:
                out.append(1.0 if missing else 0.0)
        return np.asarray(out, dtype=np.float32)

    def host_apply(self, *cols: fr.HostColumn) -> fr.HostColumn:
        n = len(cols[0])
        blocks = []
        for i, c in enumerate(cols):
            fill = np.asarray(self.fill_values[i], dtype=np.float32)
            vals = np.where(c.mask[:, None], c.values, fill[None, :]).astype(np.float32)
            if self.track_nulls:
                vals = np.concatenate(
                    [vals, (~c.mask).astype(np.float32)[:, None]], axis=1)
            blocks.append(vals)
        return fr.HostColumn(ft.OPVector, np.concatenate(blocks, axis=1),
                             meta=self._meta())

    def _meta(self) -> VectorMetadata:
        cols = []
        for f in self.input_features:
            for part in ("lat", "lon", "accuracy"):
                cols.append(VectorColumnMetadata(
                    *parent_of(f), grouping=f.name,
                    descriptor_value=part))
            if self.track_nulls:
                cols.append(VectorColumnMetadata(
                    *parent_of(f), grouping=f.name,
                    indicator_value=NULL_INDICATOR))
        return VectorMetadata(self.get_output().name, tuple(cols)).reindexed(0)

    def fitted_state(self):
        return {"fill_values": np.asarray(self.fill_values, np.float64)}

    def set_fitted_state(self, state):
        self.fill_values = [list(map(float, v)) for v in state["fill_values"]]
