"""DateList vectorization.

Parity: reference ``core/.../stages/impl/feature/DateListVectorizer.scala``
— pivots: SinceFirst / SinceLast (days relative to a reference date),
ModeDay / ModeMonth / ModeHour (most frequent calendar unit). The reference
anchors "now" at transform time; here the reference instant is an explicit
param (deterministic pipelines), defaulting to 2018-01-01 UTC.
"""

from __future__ import annotations

from collections import Counter
from typing import Optional

import numpy as np

from transmogrifai_tpu import frame as fr
from transmogrifai_tpu.stages.base import HostTransformer
from transmogrifai_tpu.types import feature_types as ft
from transmogrifai_tpu.vector_metadata import (
    parent_of,
    NULL_INDICATOR, VectorColumnMetadata, VectorMetadata,
)

__all__ = ["DateListVectorizer", "DATE_LIST_PIVOTS"]

DATE_LIST_PIVOTS = ("SinceFirst", "SinceLast", "ModeDay", "ModeMonth",
                    "ModeHour")
_MS_DAY = 86_400_000
_DEFAULT_REFERENCE_MS = 1_514_764_800_000  # 2018-01-01T00:00:00Z


class DateListVectorizer(HostTransformer):
    variadic = True
    in_types = (ft.DateList,)
    out_type = ft.OPVector

    def __init__(self, pivot: str = "SinceLast",
                 reference_date_ms: int = _DEFAULT_REFERENCE_MS,
                 track_nulls: bool = True, uid: Optional[str] = None):
        if pivot not in DATE_LIST_PIVOTS:
            raise ValueError(
                f"Unknown pivot {pivot!r}; one of {DATE_LIST_PIVOTS}")
        self.pivot = pivot
        self.reference_date_ms = reference_date_ms
        self.track_nulls = track_nulls
        super().__init__(uid=uid)

    def _value(self, dates) -> Optional[float]:
        if not dates:
            return None
        p = self.pivot
        if p == "SinceFirst":
            return (self.reference_date_ms - min(dates)) / _MS_DAY
        if p == "SinceLast":
            return (self.reference_date_ms - max(dates)) / _MS_DAY
        if p == "ModeDay":
            units = [((d // _MS_DAY) + 3) % 7 for d in dates]  # Mon=0
        elif p == "ModeMonth":
            units = [int((d / (_MS_DAY * 30.436875)) % 12) for d in dates]
        else:  # ModeHour
            units = [(d // 3_600_000) % 24 for d in dates]
        return float(Counter(units).most_common(1)[0][0])

    def transform_row(self, *values):
        out = []
        for dates in values:
            v = self._value(dates)
            out.append(0.0 if v is None else v)
            if self.track_nulls:
                out.append(1.0 if v is None else 0.0)
        return np.asarray(out, dtype=np.float32)

    def host_apply(self, *cols: fr.HostColumn) -> fr.HostColumn:
        n = len(cols[0])
        rows = [self.transform_row(*(c.values[i] for c in cols))
                for i in range(n)]
        return fr.HostColumn(ft.OPVector, np.stack(rows), meta=self._meta())

    def _meta(self) -> VectorMetadata:
        cols = []
        for f in self.input_features:
            cols.append(VectorColumnMetadata(
                *parent_of(f), grouping=f.name,
                descriptor_value=self.pivot))
            if self.track_nulls:
                cols.append(VectorColumnMetadata(
                    *parent_of(f), grouping=f.name,
                    indicator_value=NULL_INDICATOR))
        return VectorMetadata(self.get_output().name, tuple(cols)).reindexed(0)
