from transmogrifai_tpu.ops.vectorizers.numeric import (
    BinaryVectorizer, IntegralVectorizer, RealVectorizer,
)
from transmogrifai_tpu.ops.vectorizers.onehot import (
    OneHotVectorizer, SetVectorizer,
)
from transmogrifai_tpu.ops.vectorizers.hashing import TextHashingVectorizer
from transmogrifai_tpu.ops.vectorizers.dates import DateToUnitCircleVectorizer
from transmogrifai_tpu.ops.vectorizers.bucketizers import (
    DecisionTreeNumericBucketizer, DecisionTreeNumericMapBucketizer,
    NumericBucketizer, PercentileCalibrator,
)
from transmogrifai_tpu.ops.combiner import VectorsCombiner

__all__ = [
    "BinaryVectorizer", "IntegralVectorizer", "RealVectorizer",
    "OneHotVectorizer", "SetVectorizer", "TextHashingVectorizer",
    "DateToUnitCircleVectorizer", "VectorsCombiner",
    "NumericBucketizer", "DecisionTreeNumericBucketizer",
    "DecisionTreeNumericMapBucketizer", "PercentileCalibrator",
]
