"""Numeric vectorizers: N numeric features -> one OPVector block.

Parity: reference ``core/.../stages/impl/feature/{RealVectorizer (via
VectorizerDefaults), IntegralVectorizer, BinaryVectorizer}`` semantics —
mean-fill (reals) / mode-fill (integrals) / constant-fill (binaries) with
per-feature null-indicator tracking. Layout per input feature is
``[filled_value, null_indicator]`` (when track_nulls), matching the
reference's column ordering so metadata-driven consumers (SanityChecker,
ModelInsights) see the same shape of world.

TPU-first: fitting is a single fused masked-moment reduction on device; the
transform is a pure jittable map fused into its DAG layer.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from transmogrifai_tpu import frame as fr
from transmogrifai_tpu.stages.base import DeviceTransformer, Estimator
from transmogrifai_tpu.types import feature_types as ft
from transmogrifai_tpu.vector_metadata import (
    NULL_INDICATOR, VectorColumnMetadata, VectorMetadata, parent_of,
)

__all__ = ["RealVectorizer", "IntegralVectorizer", "BinaryVectorizer"]


@jax.jit
def _masked_means(values: tuple, masks: tuple):
    """One fused program for all columns' fill means (k separate reductions
    would pay k dispatch round-trips on remote devices)."""
    V = jnp.stack(values, axis=1)
    M = jnp.stack(masks, axis=1)
    return jnp.sum(V * M, axis=0) / jnp.maximum(jnp.sum(M, axis=0), 1.0)


def _numeric_vector_meta(out_name: str, input_feats, track_nulls: bool
                         ) -> VectorMetadata:
    cols = []
    for f in input_feats:
        cols.append(VectorColumnMetadata(*parent_of(f),
                                         descriptor_value=None))
        if track_nulls:
            cols.append(VectorColumnMetadata(
                *parent_of(f), indicator_value=NULL_INDICATOR))
    return VectorMetadata(out_name, tuple(cols)).reindexed(0)


class _FilledVectorizerModel(DeviceTransformer):
    """Shared model: fill missing with per-feature constants + null cols."""

    variadic = True
    out_type = ft.OPVector

    def __init__(self, fill_values: Sequence[float] = (),
                 track_nulls: bool = True, uid: Optional[str] = None):
        self.fill_values = [float(v) for v in fill_values]
        self.track_nulls = track_nulls
        super().__init__(uid=uid)

    def device_params(self):
        return jnp.asarray(self.fill_values, dtype=jnp.float32)

    def device_apply(self, params, *cols: fr.NumericColumn) -> fr.VectorColumn:
        pieces = []
        for i, c in enumerate(cols):
            filled = c.values * c.mask + params[i] * (1.0 - c.mask)
            pieces.append(filled[:, None])
            if self.track_nulls:
                pieces.append((1.0 - c.mask)[:, None])
        meta = _numeric_vector_meta(
            self.get_output().name, self.input_features, self.track_nulls)
        return fr.VectorColumn(jnp.concatenate(pieces, axis=1), meta)

    def transform_row(self, *values):
        out = []
        for i, v in enumerate(values):
            missing = v is None
            out.append(self.fill_values[i] if missing else float(v))
            if self.track_nulls:
                out.append(1.0 if missing else 0.0)
        return np.asarray(out, dtype=np.float32)

    def fitted_state(self):
        return {"fill_values": np.asarray(self.fill_values, np.float64)}

    def set_fitted_state(self, state):
        self.fill_values = [float(x) for x in state["fill_values"]]


class RealVectorizerModel(_FilledVectorizerModel):
    in_types = (ft.Real,)


class RealVectorizer(Estimator):
    """Mean-fill vectorizer over N Real-ish inputs (variadic estimator)."""

    variadic = True
    in_types = (ft.Real,)
    out_type = ft.OPVector

    def __init__(self, fill_with_mean: bool = True, fill_value: float = 0.0,
                 track_nulls: bool = True, uid: Optional[str] = None):
        self.fill_with_mean = fill_with_mean
        self.fill_value = fill_value
        self.track_nulls = track_nulls
        super().__init__(uid=uid)

    def fit_model(self, data):
        if self.fill_with_mean:
            cols = [data.device_col(n) for n in self.input_names]
            means = np.asarray(_masked_means(
                tuple(c.values for c in cols), tuple(c.mask for c in cols)),
                np.float64)
            fills = [float(m) for m in means]
        else:
            fills = [self.fill_value] * len(self.input_names)
        return RealVectorizerModel(fill_values=fills,
                                   track_nulls=self.track_nulls)


class IntegralVectorizerModel(_FilledVectorizerModel):
    in_types = (ft.Integral,)


class IntegralVectorizer(Estimator):
    """Mode-fill vectorizer over N Integral inputs."""

    variadic = True
    in_types = (ft.Integral,)
    out_type = ft.OPVector

    def __init__(self, fill_with_mode: bool = True, fill_value: int = 0,
                 track_nulls: bool = True, uid: Optional[str] = None):
        self.fill_with_mode = fill_with_mode
        self.fill_value = fill_value
        self.track_nulls = track_nulls
        super().__init__(uid=uid)

    def fit_model(self, data):
        fills = []
        for n in self.input_names:
            if not self.fill_with_mode:
                fills.append(float(self.fill_value))
                continue
            col = data.host_col(n)
            present = col.values[col.mask]
            if present.size == 0:
                fills.append(float(self.fill_value))
            else:
                vals, cnts = np.unique(present, return_counts=True)
                # most frequent; ties -> smallest value (deterministic)
                fills.append(float(vals[np.argmax(cnts)]))
        return IntegralVectorizerModel(fill_values=fills,
                                       track_nulls=self.track_nulls)


class BinaryVectorizer(_FilledVectorizerModel):
    """Stateless: fill missing booleans with ``fill_value`` + null column."""

    variadic = True
    in_types = (ft.Binary,)
    out_type = ft.OPVector

    def __init__(self, fill_value: bool = False, track_nulls: bool = True,
                 uid: Optional[str] = None):
        self.fill_value = fill_value
        super().__init__(fill_values=(), track_nulls=track_nulls, uid=uid)

    def set_input(self, *features):
        super().set_input(*features)
        self.fill_values = [float(self.fill_value)] * len(features)
        return self

    def config(self):
        return {"fill_value": self.fill_value, "track_nulls": self.track_nulls}
