"""Date/time vectorization onto the unit circle.

Parity: reference ``core/.../stages/impl/feature/DateToUnitCircleTransformer
.scala`` — a timestamp maps to (sin, cos) of its phase within a time period
(HourOfDay, DayOfWeek, DayOfMonth, DayOfYear, HourOfWeek, MonthOfYear,
WeekOfMonth, WeekOfYear), so midnight and 23:59 are neighbors.

TPU-first: the phase extraction is pure modular arithmetic on epoch millis,
jittable and fused — no calendar library on the hot path. Month-anchored
periods (DayOfMonth, MonthOfYear, WeekOfMonth) use the mean Gregorian month
(30.436875 days); the cyclic encoding is phase-accurate to within leap-drift,
which is what the model consumes. Missing dates encode as the circle center
(0,0) + a null indicator column.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np

from transmogrifai_tpu import frame as fr
from transmogrifai_tpu.stages.base import DeviceTransformer
from transmogrifai_tpu.types import feature_types as ft
from transmogrifai_tpu.vector_metadata import (
    parent_of,
    NULL_INDICATOR, VectorColumnMetadata, VectorMetadata,
)

__all__ = ["DateToUnitCircleVectorizer", "TIME_PERIODS"]

_MS_HOUR = 3600_000.0
_MS_DAY = 86_400_000.0
_MS_WEEK = 7 * _MS_DAY
_MS_MONTH = 30.436875 * _MS_DAY
_MS_YEAR = 365.2425 * _MS_DAY

# period -> (modulus ms, phase offset ms). Epoch 1970-01-01 was a Thursday;
# offset aligns DayOfWeek phase 0 to Monday.
TIME_PERIODS: dict[str, tuple[float, float]] = {
    "HourOfDay": (_MS_DAY, 0.0),
    "DayOfWeek": (_MS_WEEK, 3 * _MS_DAY),
    "HourOfWeek": (_MS_WEEK, 3 * _MS_DAY),
    "DayOfMonth": (_MS_MONTH, 0.0),
    "WeekOfMonth": (_MS_MONTH, 0.0),
    "MonthOfYear": (_MS_YEAR, 0.0),
    "DayOfYear": (_MS_YEAR, 0.0),
    "WeekOfYear": (_MS_YEAR, 0.0),
}


class DateToUnitCircleVectorizer(DeviceTransformer):
    """N date inputs -> [sin, cos][, null] per input."""

    variadic = True
    in_types = (ft.Date,)
    out_type = ft.OPVector

    def __init__(self, time_period: str = "HourOfDay",
                 track_nulls: bool = True, uid: Optional[str] = None):
        if time_period not in TIME_PERIODS:
            raise ValueError(
                f"Unknown time period {time_period!r}; one of {sorted(TIME_PERIODS)}")
        self.time_period = time_period
        self.track_nulls = track_nulls
        super().__init__(uid=uid)

    def _phase(self, ms):
        modulus, offset = TIME_PERIODS[self.time_period]
        return ((ms + offset) % modulus) / modulus * (2.0 * np.pi)

    def device_apply(self, params, *cols: fr.NumericColumn) -> fr.VectorColumn:
        pieces = []
        for c in cols:
            theta = self._phase(c.values)
            pieces.append((jnp.sin(theta) * c.mask)[:, None])
            pieces.append((jnp.cos(theta) * c.mask)[:, None])
            if self.track_nulls:
                pieces.append((1.0 - c.mask)[:, None])
        meta = self._meta()
        return fr.VectorColumn(jnp.concatenate(pieces, axis=1), meta)

    def transform_row(self, *values):
        out = []
        for v in values:
            if v is None:
                out.extend([0.0, 0.0])
            else:
                theta = float(self._phase(np.float64(v)))
                out.extend([np.sin(theta), np.cos(theta)])
            if self.track_nulls:
                out.append(1.0 if v is None else 0.0)
        return np.asarray(out, dtype=np.float32)

    def _meta(self) -> VectorMetadata:
        cols = []
        for f in self.input_features:
            for part in ("sin", "cos"):
                cols.append(VectorColumnMetadata(
                    *parent_of(f), grouping=f.name,
                    descriptor_value=f"{part}_{self.time_period}"))
            if self.track_nulls:
                cols.append(VectorColumnMetadata(
                    *parent_of(f), grouping=f.name,
                    indicator_value=NULL_INDICATOR))
        return VectorMetadata(self.get_output().name, tuple(cols)).reindexed(0)
