"""Fitted text models: count vectorization, word embeddings, topic models.

Parity targets:
- ``core/.../stages/impl/feature/OpCountVectorizer.scala`` (Spark
  CountVectorizer wrapper): vocabulary of top terms by corpus frequency with
  a document-frequency floor, TextList -> sparse count vector.
- ``core/.../stages/impl/feature/OpWord2Vec.scala`` (Spark Word2Vec
  wrapper): skip-gram embeddings, document vector = mean of token vectors.
- ``core/.../stages/impl/feature/OpLDA.scala`` (Spark LDA wrapper): online
  variational Bayes topic model over term-count vectors.

TPU-first design: vocabulary building and id-encoding are host string work
(SURVEY §7 hard part #2); the *training loops* are JAX programs — Word2Vec
is a ``lax.scan`` of negative-sampling SGD steps whose gather+matmul inner
product batches onto the MXU, and LDA's E-step is a fixed-iteration digamma
recurrence vectorized over the whole corpus (no per-document Python loop),
M-step a single [K,n]x[n,V] matmul. Neither translates Spark's
driver/executor parameter averaging: one device owns the parameters and the
data streams through in batches.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from transmogrifai_tpu import frame as fr
from transmogrifai_tpu.stages.base import Estimator, HostTransformer
from transmogrifai_tpu.types import feature_types as ft
from transmogrifai_tpu.vector_metadata import (
    parent_of, VectorColumnMetadata, VectorMetadata,
)

__all__ = ["OpCountVectorizer", "CountVectorizerModel",
           "OpWord2Vec", "Word2VecModel", "OpLDA", "LDAModel"]


def _doc_tokens(value) -> list[str]:
    """TextList value -> token list (already tokenized upstream)."""
    if value is None:
        return []
    if isinstance(value, str):
        return [value]
    return [t for t in value if t is not None]


# ---------------------------------------------------------------------------
# CountVectorizer
# ---------------------------------------------------------------------------

class OpCountVectorizer(Estimator):
    """TextList -> OPVector of per-term counts over a fitted vocabulary.

    ``min_df``: minimum number (>=1) or fraction (<1) of documents a term
    must appear in; ``vocab_size``: top terms by total corpus frequency
    (Spark CountVectorizer ordering); ``binary``: presence instead of count.
    """

    in_types = (ft.TextList,)
    out_type = ft.OPVector

    def __init__(self, vocab_size: int = 1 << 18, min_df: float = 1.0,
                 min_tf: float = 1.0, binary: bool = False,
                 uid: Optional[str] = None):
        self.vocab_size = int(vocab_size)
        self.min_df = float(min_df)
        self.min_tf = float(min_tf)
        self.binary = binary
        super().__init__(uid=uid)

    def fit_model(self, data) -> "CountVectorizerModel":
        col = data.host_col(self.input_names[0])
        tf: dict[str, int] = {}
        df: dict[str, int] = {}
        n_docs = 0
        for v in col.values:
            toks = _doc_tokens(v)
            n_docs += 1
            for t in toks:
                tf[t] = tf.get(t, 0) + 1
            for t in set(toks):
                df[t] = df.get(t, 0) + 1
        min_docs = (self.min_df if self.min_df >= 1.0
                    else self.min_df * max(n_docs, 1))
        terms = [t for t in tf if df[t] >= min_docs]
        # top by corpus frequency, ties broken lexicographically for
        # deterministic vocabularies across runs
        terms.sort(key=lambda t: (-tf[t], t))
        vocab = terms[: self.vocab_size]
        return CountVectorizerModel(vocab=vocab, min_tf=self.min_tf,
                                    binary=self.binary)


class CountVectorizerModel(HostTransformer):
    in_types = (ft.TextList,)
    out_type = ft.OPVector

    def __init__(self, vocab: Sequence[str] = (), min_tf: float = 1.0,
                 binary: bool = False, uid: Optional[str] = None):
        self.vocab = list(vocab)
        self.min_tf = float(min_tf)
        self.binary = binary
        self._index = {t: i for i, t in enumerate(self.vocab)}
        super().__init__(uid=uid)

    def transform_row(self, value):
        out = np.zeros(len(self.vocab), dtype=np.float32)
        toks = _doc_tokens(value)
        for t in toks:
            i = self._index.get(t)
            if i is not None:
                out[i] += 1.0
        # per-document term-frequency floor (Spark minTF: count or fraction)
        floor = (self.min_tf if self.min_tf >= 1.0
                 else self.min_tf * max(len(toks), 1))
        out[out < floor] = 0.0
        if self.binary:
            out = (out > 0).astype(np.float32)
        return out

    def host_apply(self, *cols: fr.HostColumn) -> fr.HostColumn:
        vals = np.stack([self.transform_row(v) for v in cols[0].values]) \
            if len(cols[0]) else np.zeros((0, len(self.vocab)), np.float32)
        return fr.HostColumn(ft.OPVector, vals.astype(np.float32),
                             meta=self._meta())

    def _meta(self) -> VectorMetadata:
        f = self.input_features[0]
        cols = tuple(VectorColumnMetadata(*parent_of(f), grouping=f.name,
                                          descriptor_value=term)
                     for term in self.vocab)
        return VectorMetadata(self.get_output().name, cols).reindexed(0)

    def config(self) -> dict:
        return {"vocab": self.vocab, "min_tf": self.min_tf,
                "binary": self.binary}


# ---------------------------------------------------------------------------
# Word2Vec
# ---------------------------------------------------------------------------

class OpWord2Vec(Estimator):
    """TextList -> OPVector document embedding (mean of token vectors).

    Skip-gram with negative sampling trained as one jitted ``lax.scan`` over
    minibatches: each step gathers (center, context, k negatives) embedding
    rows and reduces sigmoid losses — gather + batched dot products, MXU
    friendly, no Python in the loop.
    """

    in_types = (ft.TextList,)
    out_type = ft.OPVector

    def __init__(self, vector_size: int = 100, min_count: int = 5,
                 window_size: int = 5, num_iterations: int = 1,
                 num_negatives: int = 5, step_size: float = 0.025,
                 batch_size: int = 1024, max_vocab: int = 1 << 17,
                 seed: int = 42, uid: Optional[str] = None):
        self.vector_size = int(vector_size)
        self.min_count = int(min_count)
        self.window_size = int(window_size)
        self.num_iterations = int(num_iterations)
        self.num_negatives = int(num_negatives)
        self.step_size = float(step_size)
        self.batch_size = int(batch_size)
        self.max_vocab = int(max_vocab)
        self.seed = int(seed)
        super().__init__(uid=uid)

    # -- host side: vocab + pair generation ----------------------------------
    def _pairs(self, docs) -> tuple[list[str], np.ndarray, np.ndarray]:
        counts: dict[str, int] = {}
        for v in docs:
            for t in _doc_tokens(v):
                counts[t] = counts.get(t, 0) + 1
        vocab = [t for t, c in counts.items() if c >= self.min_count]
        vocab.sort(key=lambda t: (-counts[t], t))
        vocab = vocab[: self.max_vocab]
        index = {t: i for i, t in enumerate(vocab)}
        centers, contexts = [], []
        for v in docs:
            ids = [index[t] for t in _doc_tokens(v) if t in index]
            for i, c in enumerate(ids):
                lo = max(0, i - self.window_size)
                hi = min(len(ids), i + self.window_size + 1)
                for j in range(lo, hi):
                    if j != i:
                        centers.append(c)
                        contexts.append(ids[j])
        return (vocab, np.asarray(centers, np.int32),
                np.asarray(contexts, np.int32))

    def fit_model(self, data) -> "Word2VecModel":
        col = data.host_col(self.input_names[0])
        vocab, centers, contexts = self._pairs(col.values)
        v, d = len(vocab), self.vector_size
        if v == 0 or centers.size == 0:
            return Word2VecModel(vocab=vocab,
                                 vectors=np.zeros((0, d), np.float32))
        import optax

        key = jax.random.PRNGKey(self.seed)
        k_init, k_shuf, k_train = jax.random.split(key, 3)
        emb_in = (jax.random.uniform(k_init, (v, d), jnp.float32) - 0.5) / d
        emb_out = jnp.zeros((v, d), jnp.float32)

        b = min(self.batch_size, centers.size)
        n_batches = centers.size // b
        c_full = jnp.asarray(centers)
        x_full = jnp.asarray(contexts)
        kn = self.num_negatives
        opt = optax.adam(self.step_size)
        del k_shuf  # per-epoch shuffles derive from the training key

        def epoch_step(carry, batch):
            params, opt_state, key = carry
            c_ids, x_ids = batch
            key, k_neg = jax.random.split(key)
            neg = jax.random.randint(k_neg, (b, kn), 0, v)

            def loss_fn(p):
                e_i, e_o = p
                ec = e_i[c_ids]                      # [b, d]
                ox = e_o[x_ids]                      # [b, d]
                on = e_o[neg]                        # [b, kn, d]
                pos = jnp.sum(ec * ox, axis=-1)      # [b]
                negs = jnp.einsum("bd,bkd->bk", ec, on)
                return -(jnp.mean(jax.nn.log_sigmoid(pos))
                         + jnp.mean(jnp.sum(jax.nn.log_sigmoid(-negs), -1)))

            grads = jax.grad(loss_fn)(params)
            updates, opt_state = opt.update(grads, opt_state, params)
            return (optax.apply_updates(params, updates), opt_state, key), ()

        @jax.jit
        def train(params, key):
            opt_state = opt.init(params)

            def one_epoch(carry, _):
                params, opt_state, key = carry
                key, k_perm = jax.random.split(key)
                # fresh shuffle each epoch so the truncated tail rotates and
                # every pair trains across epochs
                perm = jax.random.permutation(
                    k_perm, centers.size)[: n_batches * b]
                batches = (c_full[perm].reshape(n_batches, b),
                           x_full[perm].reshape(n_batches, b))
                carry, _ = jax.lax.scan(
                    epoch_step, (params, opt_state, key), batches)
                return carry, ()

            (params, _, _), _ = jax.lax.scan(
                one_epoch, (params, opt_state, key), None,
                length=self.num_iterations)
            return params[0]

        vectors = np.asarray(train((emb_in, emb_out), k_train))
        return Word2VecModel(vocab=vocab, vectors=vectors)


class Word2VecModel(HostTransformer):
    in_types = (ft.TextList,)
    out_type = ft.OPVector

    def __init__(self, vocab: Sequence[str] = (),
                 vectors: Optional[np.ndarray] = None,
                 uid: Optional[str] = None):
        self.vocab = list(vocab)
        self.vectors = (np.zeros((0, 0), np.float32) if vectors is None
                        else np.asarray(vectors, np.float32))
        self._index = {t: i for i, t in enumerate(self.vocab)}
        super().__init__(uid=uid)

    @property
    def vector_size(self) -> int:
        return self.vectors.shape[1] if self.vectors.size else 0

    def transform_row(self, value):
        d = self.vector_size
        ids = [self._index[t] for t in _doc_tokens(value) if t in self._index]
        if not ids or d == 0:
            return np.zeros(d, np.float32)
        return self.vectors[ids].mean(axis=0)

    def host_apply(self, *cols: fr.HostColumn) -> fr.HostColumn:
        d = self.vector_size
        vals = (np.stack([self.transform_row(v) for v in cols[0].values])
                if len(cols[0]) else np.zeros((0, d), np.float32))
        f = self.input_features[0]
        meta = VectorMetadata(self.get_output().name, tuple(
            VectorColumnMetadata(*parent_of(f), grouping=f.name,
                                 descriptor_value=f"w2v_{j}")
            for j in range(d))).reindexed(0)
        return fr.HostColumn(ft.OPVector, vals.astype(np.float32), meta=meta)

    def config(self) -> dict:
        return {"vocab": self.vocab}

    def fitted_state(self) -> dict:
        return {"vectors": self.vectors}

    def set_fitted_state(self, state: dict) -> None:
        self.vectors = np.asarray(state["vectors"], np.float32)


# ---------------------------------------------------------------------------
# LDA
# ---------------------------------------------------------------------------

def _lda_e_step(lam: jnp.ndarray, x: jnp.ndarray, alpha: float,
                n_iter: int = 30):
    """Variational E-step for all docs at once: gamma [n, K]."""
    from jax.scipy.special import digamma

    e_log_beta = digamma(lam) - digamma(lam.sum(1, keepdims=True))  # [K, V]
    exp_elog_beta = jnp.exp(e_log_beta)                             # [K, V]

    def body(gamma, _):
        e_log_theta = digamma(gamma) - digamma(gamma.sum(1, keepdims=True))
        exp_elog_theta = jnp.exp(e_log_theta)                       # [n, K]
        # phi normalizer per (doc, word): [n, V]
        norm = exp_elog_theta @ exp_elog_beta + 1e-30
        gamma_new = alpha + exp_elog_theta * ((x / norm) @ exp_elog_beta.T)
        return gamma_new, ()

    n, k = x.shape[0], lam.shape[0]
    gamma0 = jnp.ones((n, k), jnp.float32)
    gamma, _ = jax.lax.scan(body, gamma0, None, length=n_iter)
    return gamma, exp_elog_beta


class OpLDA(Estimator):
    """OPVector (term counts) -> OPVector (topic mixture).

    Batch variational Bayes (the full-corpus case of Hoffman's online VB):
    E-step is a fixed-iteration scan over digamma updates vectorized across
    every document simultaneously; M-step one matmul. Everything jitted.
    """

    in_types = (ft.OPVector,)
    out_type = ft.OPVector

    def __init__(self, k: int = 10, max_iter: int = 20,
                 doc_concentration: Optional[float] = None,
                 topic_concentration: Optional[float] = None,
                 seed: int = 42, uid: Optional[str] = None):
        self.k = int(k)
        self.max_iter = int(max_iter)
        self.doc_concentration = doc_concentration
        self.topic_concentration = topic_concentration
        self.seed = int(seed)
        super().__init__(uid=uid)

    def fit_model(self, data) -> "LDAModel":
        col = data.device_col(self.input_names[0])
        x = jnp.asarray(col.values, jnp.float32)
        n, v = x.shape
        k = self.k
        # Spark default ~ 1/k; explicit values must be positive (0 drives
        # the digamma recurrence to -inf)
        alpha = (1.0 / k if self.doc_concentration is None
                 else float(self.doc_concentration))
        eta = (1.0 / k if self.topic_concentration is None
               else float(self.topic_concentration))
        if alpha <= 0 or eta <= 0:
            raise ValueError("doc/topic concentration must be positive")
        key = jax.random.PRNGKey(self.seed)
        lam0 = jax.random.gamma(key, 100.0, (k, v)) / 100.0

        @jax.jit
        def train(lam):
            def one_iter(lam, _):
                gamma, exp_elog_beta = _lda_e_step(lam, x, alpha)
                from jax.scipy.special import digamma
                e_log_theta = digamma(gamma) - digamma(
                    gamma.sum(1, keepdims=True))
                exp_elog_theta = jnp.exp(e_log_theta)
                norm = exp_elog_theta @ exp_elog_beta + 1e-30
                # sufficient stats: [K, V]
                stats = exp_elog_beta * (exp_elog_theta.T @ (x / norm))
                return eta + stats, ()
            lam, _ = jax.lax.scan(one_iter, lam, None, length=self.max_iter)
            return lam

        lam = np.asarray(train(lam0))
        return LDAModel(topics=lam, doc_concentration=float(alpha))


class LDAModel(HostTransformer):
    """Inference: normalized variational gamma = E[theta | doc]."""

    in_types = (ft.OPVector,)
    out_type = ft.OPVector

    def __init__(self, topics: Optional[np.ndarray] = None,
                 doc_concentration: float = 0.1,
                 uid: Optional[str] = None):
        self.topics = (np.zeros((0, 0), np.float32) if topics is None
                       else np.asarray(topics, np.float32))
        self.doc_concentration = float(doc_concentration)
        super().__init__(uid=uid)

    @property
    def k(self) -> int:
        return self.topics.shape[0]

    def _infer(self, x: np.ndarray) -> np.ndarray:
        gamma, _ = _lda_e_step(jnp.asarray(self.topics),
                               jnp.asarray(x, jnp.float32),
                               self.doc_concentration)
        g = np.asarray(gamma)
        return g / np.maximum(g.sum(axis=1, keepdims=True), 1e-30)

    def transform_row(self, value):
        x = np.asarray(value, np.float32).reshape(1, -1)
        return self._infer(x)[0]

    def host_apply(self, *cols: fr.HostColumn) -> fr.HostColumn:
        x = np.asarray(cols[0].values, np.float32)
        vals = (self._infer(x) if x.shape[0]
                else np.zeros((0, self.k), np.float32))
        f = self.input_features[0]
        meta = VectorMetadata(self.get_output().name, tuple(
            VectorColumnMetadata(*parent_of(f), grouping=f.name,
                                 descriptor_value=f"topic_{j}")
            for j in range(self.k))).reindexed(0)
        return fr.HostColumn(ft.OPVector, vals.astype(np.float32), meta=meta)

    def config(self) -> dict:
        return {"doc_concentration": self.doc_concentration}

    def fitted_state(self) -> dict:
        return {"topics": self.topics}

    def set_fitted_state(self, state: dict) -> None:
        self.topics = np.asarray(state["topics"], np.float32)
