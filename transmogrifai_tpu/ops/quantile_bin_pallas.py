"""Pallas TPU kernel for quantile/fixed-split bucketization.

The bucketizer transform (``ops/vectorizers/bucketizers._bucketize_block``)
is a bin-edge search over the fitted splits followed by a one-hot expand:

    idx[r]  = #{j : inner_split[j] <= v[r]}            (searchsorted right)
    slot[r] = idx | invalid | null                      (range + mask rules)
    out     = one_hot(slot, width)                      [n, width] f32

The XLA path materializes the searchsorted gather + one-hot as separate
HLOs; at Criteo widths (13 numeric columns x ~34-bucket tree splits inside
one fused FE program) the one-hot scatter is pure VPU work that this kernel
keeps entirely in VMEM: one grid step = one row block, the split vector
(tiny, <= a few hundred f32) replicated into VMEM, bin index by comparison
count and the one-hot written as a single iota-compare — no intermediate
index array ever reaches HBM.

Engine selection mirrors the sorted-histogram kernel
(``ops/sorted_hist_pallas.py``): ``TRANSMOGRIFAI_BUCKET_ENGINE`` picks
``pallas`` / ``xla`` / ``auto`` (auto = pallas on TPU backends, xla
elsewhere); CPU CI runs the kernel in interpret mode and asserts BITWISE
parity with the XLA path (`tests/test_ingest_fusion.py`). The kernel is
stateless per grid step, so ``vmap`` batching (a future stacked use) stays
legal.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["bucketize_block", "bucketize_block_xla", "bucket_engine"]

#: rows per kernel grid step (one VMEM-resident block)
_BLOCK_ROWS = 1024


def bucket_engine() -> str:
    """Resolved engine: ``pallas`` | ``xla``. ``auto`` (default) picks
    pallas only on TPU backends — the XLA path is the portable
    fallback every CPU run takes."""
    eng = os.environ.get("TRANSMOGRIFAI_BUCKET_ENGINE", "auto")
    if eng not in ("auto", "pallas", "xla"):
        raise ValueError(
            f"TRANSMOGRIFAI_BUCKET_ENGINE={eng!r}; one of auto|pallas|xla")
    if eng == "auto":
        return "pallas" if jax.default_backend() == "tpu" else "xla"
    return eng


def bucketize_block_xla(values, mask, splits: np.ndarray,
                        track_invalid: bool, track_nulls: bool):
    """Pure-XLA reference path (the pre-round-14 ``_bucketize_block``
    math, verbatim): jittable one-hot bucket block for one numeric
    column. Layout: [bucket_0..bucket_{k-1}, invalid?, null?]."""
    k = len(splits) - 1
    inner = jnp.asarray(splits[1:-1], jnp.float32)
    idx = jnp.searchsorted(inner, values, side="right") if k > 1 else (
        jnp.zeros(values.shape, jnp.int32))
    in_range = (values >= splits[0]) & (values <= splits[-1])
    width = k + int(track_invalid) + int(track_nulls)
    # slot: bucket for valid, k for invalid, k+trackInvalid for null,
    # `width` (one-hot of width drops it) for untracked cases
    invalid_slot = k if track_invalid else width
    null_slot = k + int(track_invalid) if track_nulls else width
    slot = jnp.where(in_range, idx, invalid_slot)
    slot = jnp.where(mask > 0, slot, null_slot)
    return jax.nn.one_hot(slot, width, dtype=jnp.float32)


def _kernel(v_ref, m_ref, sp_ref, out_ref, *, k: int, width: int,
            invalid_slot: int, null_slot: int):
    """One grid step = one row block, fully VMEM-resident.

    The bin-edge search is a comparison COUNT against the inner splits
    (sum over j of v >= inner[j] == searchsorted side="right"), the
    range/null slot rules match the XLA path exactly, and the one-hot is
    a single [R, width] iota compare — all VPU element-wise work."""
    v = v_ref[0]                      # [R] f32
    m = m_ref[0]                      # [R] f32
    sp = sp_ref[...]                  # [k+1] f32 (fitted splits, +-inf ends)
    R = v.shape[0]
    idx = jnp.zeros((R,), jnp.int32)
    for j in range(1, k):             # static unroll over the inner splits
        idx = idx + (v >= sp[j]).astype(jnp.int32)
    in_range = (v >= sp[0]) & (v <= sp[k])
    slot = jnp.where(in_range, idx, invalid_slot)
    slot = jnp.where(m > 0, slot, null_slot)
    lanes = jax.lax.broadcasted_iota(jnp.int32, (R, width), 1)
    out_ref[0] = (lanes == slot[:, None]).astype(jnp.float32)


@functools.partial(
    jax.jit, static_argnames=("k", "track_invalid", "track_nulls",
                              "interpret"))
def _bucketize_pallas(values, mask, splits, *, k: int, track_invalid: bool,
                      track_nulls: bool, interpret: bool):
    n = values.shape[0]
    width = k + int(track_invalid) + int(track_nulls)
    invalid_slot = k if track_invalid else width
    null_slot = k + int(track_invalid) if track_nulls else width
    R = min(_BLOCK_ROWS, max(int(n), 1))
    n_pad = int(np.ceil(max(n, 1) / R) * R)
    # padded rows carry mask 0 -> null_slot (or all-zeros): harmless, and
    # sliced back off below
    v = jnp.pad(values.astype(jnp.float32), (0, n_pad - n))
    m = jnp.pad(mask.astype(jnp.float32), (0, n_pad - n))
    nb = n_pad // R
    out = pl.pallas_call(
        functools.partial(_kernel, k=k, width=width,
                          invalid_slot=invalid_slot, null_slot=null_slot),
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((1, R), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, R), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((k + 1,), lambda i: (0,),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, R, width), lambda i: (i, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((nb, R, width), jnp.float32),
        interpret=interpret,
    )(v.reshape(nb, R), m.reshape(nb, R), splits)
    return out.reshape(n_pad, width)[:n]


def bucketize_block(values, mask, splits: np.ndarray, track_invalid: bool,
                    track_nulls: bool, engine: str | None = None,
                    interpret: bool | None = None):
    """Engine-dispatched bucket block (see module docstring). ``engine``
    overrides the env-resolved default; ``interpret`` forces the pallas
    interpreter (CPU parity tests). Degenerate shapes (no splits, k < 1)
    keep the XLA path — there is nothing for a kernel to win there."""
    eng = engine or bucket_engine()
    k = len(splits) - 1
    if eng != "pallas" or k < 1:
        return bucketize_block_xla(values, mask, splits,
                                   track_invalid, track_nulls)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _bucketize_pallas(
        values, mask, jnp.asarray(splits, jnp.float32), k=k,
        track_invalid=bool(track_invalid), track_nulls=bool(track_nulls),
        interpret=bool(interpret))
