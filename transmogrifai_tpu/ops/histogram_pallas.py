"""Pallas TPU kernel: (node, feature, bin) gradient/hessian histograms.

The tree learner's hot op (models/trees.py grow_tree) needs, per level,

    hist[s, node, f, b] = sum_n s_n(grad|hess) * 1[node_n == node] * 1[Xb_nf == b]

The pure-XLA path is a scatter-add, which serializes on TPU. This kernel
recasts it as compare + matmul: for a (feature-tile, row-chunk) grid cell it
builds the one-hot of the combined ``node*B + bin`` index in VMEM (never in
HBM) and contracts it with the [grad; hess] rows on the MXU. That is the
canonical MXU-friendly histogram (the analog of what libxgboost's GPU
backend does with shared-memory atomics — here atomics become a matmul).

Parity: replaces the executor-distributed histogram aggregation of Spark
MLlib trees / XGBoost's Rabit all-reduce (SURVEY §2.7 P5). Under a mesh the
kernel runs per shard and the [2, d, K] output is psum'd over ICI.

Falls back to interpret mode off-TPU so the same code path runs in CPU CI.

Measured on the real chip (TPU v5 lite, round 2, 1M rows x 28 features x
64 bins): isolated per-call microbenchmarks are dispatch-dominated and
unreliable through the device tunnel, but the macro number is decisive — a
full 50-tree depth-12 ensemble (600 scatter levels) executes in ~4s device
time, ~6ms/level, so the in-scan XLA scatter is NOT the serialization
bottleneck the round-1 design anticipated. Separately, Mosaic's tiling
rules require an 8-sublane feature tile, capping the kernel's one-hot at
node*bin <= 768 (8 nodes at 64 bins) — deeper levels cannot lower. The
scatter path therefore stays the default; the kernel remains for the
shallow levels where it lowers legally and as the exemplar MXU-histogram
recipe (compare+matmul beats scatter ~10x when called standalone at
node counts <= 8).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["node_bin_histogram", "node_bin_histogram_xla"]

#: VMEM budget for the one-hot tile (bytes); F_T adapts to stay under it
_EQ_BUDGET = 6 * 1024 * 1024
_CHUNK = 256  # rows per grid step (lane dim of the one-hot contraction)


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def _kernel(xb_ref, node_ref, gh_ref, out_ref, *, n_bins: int, K: int):
    """Everything stays 2D (Mosaic layout-friendly): per feature of the
    tile, a [C, K] one-hot compare feeds one (2xC)@(CxK) MXU matmul."""
    j = pl.program_id(1)
    F_T, C = xb_ref.shape
    comb = xb_ref[:, :] + node_ref[0, :][None, :] * n_bins      # [F_T, C]
    k_iota = jax.lax.broadcasted_iota(jnp.int32, (C, K), 1)     # [C, K]
    for f in range(F_T):  # static, small: unrolled into the program
        eqf = (comb[f, :][:, None] == k_iota).astype(jnp.float32)
        part = jnp.dot(gh_ref[:, :], eqf,
                       preferred_element_type=jnp.float32)      # [2, K]

        @pl.when(j == 0)
        def _(part=part, f=f):
            out_ref[:, pl.ds(f * K, K)] = part

        @pl.when(j > 0)
        def _(part=part, f=f):
            out_ref[:, pl.ds(f * K, K)] = out_ref[:, pl.ds(f * K, K)] + part


def node_bin_histogram(Xb, node, grad, hess, *, n_nodes: int, n_bins: int,
                       interpret: bool | None = None):
    """[n_nodes, d, B] grad and hess histograms via the Pallas kernel.

    Xb: [n, d] int32 bin codes in [0, B); node: [n] int32 in [0, n_nodes);
    grad/hess: [n] f32 (row weights already applied). ``interpret=None``
    compiles on TPU and interprets elsewhere (CPU CI runs the same path).
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    # Mosaic requires the feature tile be a multiple of 8 sublanes; with the
    # one-hot tile at [8, K*_CHUNK] floats, K beyond the VMEM budget cannot
    # lower — those deep levels take the scatter path instead
    if not interpret and n_nodes * n_bins * _CHUNK * 4 * 8 > _EQ_BUDGET:
        return node_bin_histogram_xla(Xb, node, grad, hess,
                                      n_nodes=n_nodes, n_bins=n_bins)
    return _node_bin_histogram(Xb, node, grad, hess, n_nodes=n_nodes,
                               n_bins=n_bins, interpret=interpret)


@functools.partial(jax.jit, static_argnames=("n_nodes", "n_bins", "interpret"))
def _node_bin_histogram(Xb, node, grad, hess, *, n_nodes: int, n_bins: int,
                        interpret: bool):
    n, d = Xb.shape
    K = n_nodes * n_bins
    # feature-tile size bounded by the VMEM one-hot budget; Mosaic needs a
    # multiple of 8 sublanes, so 8 is both floor and (practical) ceiling
    F_T = 8
    n_pad = _round_up(max(n, 1), _CHUNK)
    d_pad = _round_up(max(d, 1), F_T)

    xb_t = jnp.zeros((d_pad, n_pad), jnp.int32)
    xb_t = xb_t.at[:d, :n].set(Xb.T)
    node_p = jnp.zeros((1, n_pad), jnp.int32).at[0, :n].set(node)
    gh = jnp.zeros((2, n_pad), jnp.float32)
    gh = gh.at[0, :n].set(grad).at[1, :n].set(hess)

    grid = (d_pad // F_T, n_pad // _CHUNK)
    out = pl.pallas_call(
        functools.partial(_kernel, n_bins=n_bins, K=K),
        grid=grid,
        in_specs=[
            pl.BlockSpec((F_T, _CHUNK), lambda i, j: (i, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, _CHUNK), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((2, _CHUNK), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((2, F_T * K), lambda i, j: (0, i),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((2, d_pad * K), jnp.float32),
        interpret=interpret,
    )(xb_t, node_p, gh)

    # [2, d*K] -> [2, d, n_nodes, B] -> ([n_nodes, d, B], [n_nodes, d, B])
    hist = out.reshape(2, d_pad, n_nodes, n_bins)[:, :d]
    hist = jnp.transpose(hist, (0, 2, 1, 3))
    return hist[0], hist[1]


@functools.partial(jax.jit, static_argnames=("n_nodes", "n_bins"))
def node_bin_histogram_xla(Xb, node, grad, hess, *, n_nodes: int,
                           n_bins: int):
    """Scatter-add reference (the pre-Pallas path; also the parity oracle)."""
    n, d = Xb.shape
    flat = ((node[:, None] * d + jnp.arange(d)[None, :]) * n_bins
            + Xb).reshape(-1)
    seg = n_nodes * d * n_bins
    hg = jnp.zeros(seg, jnp.float32).at[flat].add(
        jnp.broadcast_to(grad[:, None], (n, d)).reshape(-1))
    hh = jnp.zeros(seg, jnp.float32).at[flat].add(
        jnp.broadcast_to(hess[:, None], (n, d)).reshape(-1))
    return (hg.reshape(n_nodes, d, n_bins), hh.reshape(n_nodes, d, n_bins))
