"""Remaining model families: naive Bayes, MLP, generalized linear models,
isotonic calibration.

Parity: reference ``OpNaiveBayes`` (Spark multinomial NB),
``OpMultilayerPerceptronClassifier`` (Spark MLP),
``OpGeneralizedLinearRegression`` (Spark GLR families/links), and
``IsotonicRegressionCalibrator`` (Spark IsotonicRegression on scores).

All device-native: NB fits with one ``onehot(y)^T @ X`` matmul; the MLP is a
hand-rolled (no flax) Adam ``lax.scan``; GLR runs family NLL gradient
descent; isotonic uses host PAV (tiny data: one point per distinct score).
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax

from transmogrifai_tpu import frame as fr
from transmogrifai_tpu.models.base import PredictionModel, Predictor
from transmogrifai_tpu.stages.base import Estimator
from transmogrifai_tpu.types import feature_types as ft

__all__ = [
    "OpNaiveBayes", "NaiveBayesModel",
    "OpMultilayerPerceptronClassifier", "MLPModel",
    "OpGeneralizedLinearRegression", "GLMModel",
    "IsotonicRegressionCalibrator", "IsotonicCalibratorModel",
]


# ---------------------------------------------------------------------------
# Multinomial naive Bayes
# ---------------------------------------------------------------------------

class NaiveBayesModel(PredictionModel):
    def __init__(self, log_prior=None, log_theta=None,
                 uid: Optional[str] = None):
        # may be device arrays during the CV sweep (no host pull);
        # conversion happens lazily on serialization/introspection
        self.log_prior = log_prior if log_prior is not None else np.zeros(2)
        self.log_theta = log_theta if log_theta is not None \
            else np.zeros((0, 2))
        super().__init__(uid=uid)

    def device_params(self):
        return (jnp.asarray(self.log_prior, jnp.float32),
                jnp.asarray(self.log_theta, jnp.float32))

    def quantize_device_params(self, precision):
        if precision != "int8":
            return None
        from transmogrifai_tpu.utils.precision import quantize_weights
        log_prior, log_theta = self.device_params()
        return (log_prior, quantize_weights(log_theta))

    def device_apply(self, params, col: fr.VectorColumn) -> fr.PredictionColumn:
        log_prior, log_theta = params
        X = jnp.maximum(col.values, 0.0)  # multinomial NB needs counts
        logits = X @ log_theta + log_prior
        prob = jax.nn.softmax(logits, axis=-1)
        pred = jnp.argmax(logits, axis=-1).astype(jnp.float32)
        return fr.PredictionColumn(pred, logits, prob)

    def fitted_state(self):
        return {"log_prior": np.asarray(self.log_prior, np.float64),
                "log_theta": np.asarray(self.log_theta, np.float64)}

    def set_fitted_state(self, state):
        self.log_prior = np.asarray(state["log_prior"], np.float64)
        self.log_theta = np.asarray(state["log_theta"], np.float64)

    def config(self):
        return {}

    @classmethod
    def from_config(cls, config, uid=None):
        return cls(uid=uid)

    def feature_contributions(self):
        lt = np.asarray(self.log_theta)
        return lt[:, -1] - lt[:, 0] if lt.shape[1] >= 2 else lt[:, 0]


@functools.partial(jax.jit, static_argnames=("n_classes",))
def _nb_fit(X, y, w, smoothing, *, n_classes: int):
    """One multinomial-NB closed-form fit (smoothing traced so the same
    program serves every grid point and vmaps over folds)."""
    Y = jax.nn.one_hot(y.astype(jnp.int32), n_classes) * w[:, None]
    Xp = jnp.maximum(X, 0.0)
    class_counts = jnp.sum(Y, axis=0)                      # [C]
    feat_counts = Xp.T @ Y                                 # [d, C]
    log_prior = jnp.log(class_counts / jnp.sum(class_counts))
    totals = jnp.sum(feat_counts, axis=0, keepdims=True)
    d = X.shape[1]
    log_theta = jnp.log((feat_counts + smoothing)
                        / (totals + smoothing * d))
    return log_prior, log_theta


class OpNaiveBayes(Predictor):
    """Multinomial NB with Laplace smoothing. Negative feature values are
    clipped to zero (Spark NB rejects them outright; clipping keeps the
    one-hot/hashed-count columns NB actually suits)."""

    default_params = {"smoothing": 1.0}

    def fit_arrays(self, X, y, w, params):
        smoothing = float(params.get("smoothing", 1.0))
        n_classes = max(int(np.asarray(jnp.max(y))) + 1, 2)
        log_prior, log_theta = _nb_fit(X, y, w, jnp.float32(smoothing),
                                       n_classes=n_classes)
        return NaiveBayesModel(log_prior=np.asarray(log_prior),
                               log_theta=np.asarray(log_theta))

    def grid_predict_scores(self, models, X):
        """[G, n] binary log-odds margins (None for multiclass) — the same
        batched metric program the fold-stacked path uses, so both sweep
        paths score identically."""
        if not models:
            return None
        lt = jnp.stack([jnp.asarray(m.log_theta, jnp.float32)
                        for m in models])
        lp = jnp.stack([jnp.asarray(m.log_prior, jnp.float32)
                        for m in models])
        if lt.shape[-1] != 2:
            return None
        logits = jnp.einsum("nd,gdc->gnc", jnp.maximum(X, 0.0), lt) \
            + lp[:, None, :]
        return logits[..., 1] - logits[..., 0]

    # -- fold-stacked sweep --------------------------------------------------
    def grid_fit_arrays_folds(self, X, y, w, grid, _n_classes=None):
        """Closed-form fit vmapped over (fold x smoothing grid) — one
        program for the whole family sweep; model params stay on device.
        ``_n_classes`` elides the class-count sync on the one-sync
        dispatch path (the selector's once-per-sweep hint). NB's refit
        stays the cold closed form — a one-matmul fit has nothing to warm
        start."""
        if not grid:
            return []
        n_classes = (int(_n_classes) if _n_classes is not None
                     else max(int(np.asarray(jnp.max(y))) + 1, 2))
        sm = jnp.asarray([float({**self.params, **g}.get("smoothing", 1.0))
                          for g in grid], jnp.float32)
        inner = lambda Xk, yk, wk: jax.vmap(  # noqa: E731
            lambda s: _nb_fit(Xk, yk, wk, s, n_classes=n_classes))(sm)
        lp, lt = jax.vmap(inner)(X, y, w)  # [k, G, C], [k, G, d, C]
        return [[NaiveBayesModel(log_prior=lp[f, j], log_theta=lt[f, j])
                 for j in range(len(grid))] for f in range(int(X.shape[0]))]

    def grid_predict_scores_folds(self, models, X):
        """[k, G, n_va] binary log-odds margins (None for multiclass)."""
        if not models or not models[0]:
            return None
        lt = jnp.stack([jnp.stack([jnp.asarray(m.log_theta, jnp.float32)
                                   for m in row]) for row in models])
        lp = jnp.stack([jnp.stack([jnp.asarray(m.log_prior, jnp.float32)
                                   for m in row]) for row in models])
        if lt.shape[-1] != 2:
            return None
        logits = jnp.einsum("knd,kgdc->kgnc", jnp.maximum(X, 0.0), lt) \
            + lp[:, :, None, :]
        return logits[..., 1] - logits[..., 0]


# ---------------------------------------------------------------------------
# Multilayer perceptron
# ---------------------------------------------------------------------------

def _mlp_descent(X, y, w, params0, *, max_iter: int, step_size):
    """Adam descent from explicit layer parameters (shared by the cold
    ``_train_mlp`` and the warm-started winner refit)."""
    n = X.shape[0]
    wsum = jnp.maximum(jnp.sum(w), 1.0)

    def forward(params, x):
        h = x
        for (W, b) in params[:-1]:
            h = jnp.tanh(h @ W + b)
        W, b = params[-1]
        return h @ W + b

    def loss(params):
        logits = forward(params, X)
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -logp[jnp.arange(n), y.astype(jnp.int32)]
        return jnp.sum(nll * w) / wsum

    opt = optax.adam(step_size)
    state0 = opt.init(params0)

    def step(carry, _):
        params, opt_state = carry
        l, grads = jax.value_and_grad(loss)(params)
        updates, opt_state = opt.update(grads, opt_state)
        return (optax.apply_updates(params, updates), opt_state), l

    (params, _), _ = jax.lax.scan(step, (params0, state0), None,
                                  length=max_iter)
    return params


@functools.partial(jax.jit, static_argnames=("layers", "max_iter", "seed"))
def _train_mlp(X, y, w, *, layers: tuple, max_iter: int, seed: int,
               step_size):
    d = X.shape[1]
    sizes = (d,) + layers
    key = jax.random.PRNGKey(seed)
    keys = jax.random.split(key, len(sizes) - 1)
    params0 = []
    for i, k in enumerate(keys):
        scale = jnp.sqrt(2.0 / sizes[i])
        params0.append((jax.random.normal(k, (sizes[i], sizes[i + 1]))
                        * scale, jnp.zeros(sizes[i + 1])))
    return _mlp_descent(X, y, w, params0, max_iter=max_iter,
                        step_size=step_size)


def _train_mlp_from(X, y, w, params0, *, max_iter: int, step_size):
    """Warm-started MLP refit (round 9): the same descent initialized
    from the fold-averaged winning-lane parameters instead of a fresh
    PRNG draw."""
    return _mlp_descent(X, y, w, params0, max_iter=max_iter,
                        step_size=step_size)


_MLP_WARM = None


def _mlp_warm_program():
    """Donated-buffer compiled warm MLP refit (argnum 3 = the init
    parameter pytree, consumed exactly once)."""
    global _MLP_WARM
    if _MLP_WARM is None:
        from transmogrifai_tpu.models.base import compile_refit
        _MLP_WARM = compile_refit(_train_mlp_from, donate_argnums=(3,),
                                  static_argnames=("max_iter",))
    return _MLP_WARM


class MLPModel(PredictionModel):
    def __init__(self, params=None, uid: Optional[str] = None):
        self.params = params or []  # list[(W, b)] as np arrays
        super().__init__(uid=uid)

    def device_params(self):
        return tuple((jnp.asarray(W, jnp.float32), jnp.asarray(b, jnp.float32))
                     for W, b in self.params)

    def quantize_device_params(self, precision):
        if precision != "int8":
            return None
        from transmogrifai_tpu.utils.precision import quantize_weights
        return tuple((quantize_weights(W), b) for W, b in self.device_params())

    def device_apply(self, params, col: fr.VectorColumn) -> fr.PredictionColumn:
        h = col.values
        for (W, b) in params[:-1]:
            h = jnp.tanh(h @ W + b)
        W, b = params[-1]
        logits = h @ W + b
        prob = jax.nn.softmax(logits, axis=-1)
        pred = jnp.argmax(logits, axis=-1).astype(jnp.float32)
        return fr.PredictionColumn(pred, logits, prob)

    def fitted_state(self):
        state = {"n_layers": np.asarray(len(self.params))}
        for i, (W, b) in enumerate(self.params):
            state[f"W{i}"] = np.asarray(W)
            state[f"b{i}"] = np.asarray(b)
        return state

    def set_fitted_state(self, state):
        n = int(state["n_layers"])
        self.params = [(np.asarray(state[f"W{i}"]), np.asarray(state[f"b{i}"]))
                       for i in range(n)]

    def config(self):
        return {}

    @classmethod
    def from_config(cls, config, uid=None):
        return cls(uid=uid)


class OpMultilayerPerceptronClassifier(Predictor):
    default_params = {"layers": (10, 10), "max_iter": 200,
                      "step_size": 0.01, "seed": 42}

    def fit_arrays(self, X, y, w, params):
        p = {**self.default_params, **params}
        n_classes = max(int(np.asarray(jnp.max(y))) + 1, 2)
        layers = tuple(int(x) for x in p["layers"]) + (n_classes,)
        trained = _train_mlp(X, y, w, layers=layers,
                             max_iter=int(p["max_iter"]),
                             seed=int(p["seed"]),
                             step_size=jnp.float32(p["step_size"]))
        return MLPModel(params=[(np.asarray(W), np.asarray(b))
                                for W, b in trained])

    def grid_predict_scores(self, models, X):
        """[G, n] binary margins when all grid models share layer shapes
        (None otherwise) — keeps both sweep paths on one metric program."""
        folds = self.grid_predict_scores_folds([models], X[None])
        return None if folds is None else folds[0]

    def fold_stack_unit_width(self, grid):
        """Hidden activations dominate the MLP's per-row residency: the
        widest layer (x2 for forward+grad) across the grid."""
        widths = [max(tuple({**self.default_params, **self.params, **g}
                            ["layers"]) or (1,)) for g in grid] or [1]
        return 2 * max(widths) + 4

    # -- fold-stacked sweep --------------------------------------------------
    def grid_fit_arrays_folds(self, X, y, w, grid, _n_classes=None):
        """Fold-stacked MLP sweep: step_size is the traced grid axis, one
        vmap-of-vmap Adam program per distinct (layers, max_iter, seed)
        combo; fitted params stay device views. ``_n_classes`` elides the
        class-count sync (the selector's once-per-sweep hint)."""
        if not grid:
            return []
        merged = [{**self.default_params, **self.params, **g} for g in grid]
        n_classes = (int(_n_classes) if _n_classes is not None
                     else max(int(np.asarray(jnp.max(y))) + 1, 2))
        k = int(X.shape[0])
        models: list[list] = [[None] * len(grid) for _ in range(k)]
        by_kw: dict[tuple, list[int]] = {}
        for i, p in enumerate(merged):
            layers = tuple(int(x) for x in p["layers"]) + (n_classes,)
            by_kw.setdefault((layers, int(p["max_iter"]), int(p["seed"])),
                             []).append(i)
        for (layers, mi, seed), idxs in by_kw.items():
            ss = jnp.asarray([float(merged[i]["step_size"]) for i in idxs],
                             jnp.float32)
            inner = lambda Xk, yk, wk, _l=layers, _m=mi, _s=seed: jax.vmap(  # noqa: E731,E501
                lambda s: _train_mlp(Xk, yk, wk, layers=_l, max_iter=_m,
                                     seed=_s, step_size=s))(ss)
            trained = jax.vmap(inner)(X, y, w)  # leaves [k, g, ...]
            for f in range(k):
                for j, i in enumerate(idxs):
                    models[f][i] = MLPModel(
                        params=[(W[f, j], b[f, j]) for W, b in trained])
        return models

    def grid_predict_scores_folds(self, models, X):
        """[k, G, n_va] binary margins via one stacked forward pass; None
        when grid models have heterogeneous layer shapes or >2 classes."""
        if not models or not models[0]:
            return None
        shapes = {tuple((tuple(W.shape), tuple(b.shape)) for W, b in m.params)
                  for row in models for m in row}
        if len(shapes) != 1:
            return None
        rows = [jax.tree_util.tree_map(lambda *xs: jnp.stack(xs),
                                       *[m.params for m in row])
                for row in models]
        stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *rows)

        def fwd(params, Xk):
            h = Xk
            for (W, b) in params[:-1]:
                h = jnp.tanh(h @ W + b)
            W, b = params[-1]
            return h @ W + b

        z = jax.vmap(lambda p_row, Xk: jax.vmap(
            lambda p: fwd(p, Xk))(p_row))(stacked, X)  # [k, G, n, C]
        if z.shape[-1] != 2:
            return None
        return z[..., 1] - z[..., 0]

    def grid_scores_folds_retained(self, X, y, w, grid, Xva,
                                   _n_classes=None):
        """One-sync dispatch unit: stacked scores plus the ``[k][G]``
        fitted-model nest retained as the warm-refit handle (the layer
        parameters are device views of the stacked result). A subclass
        overriding ``grid_scores_folds`` keeps its semantics (delegate,
        no warm handle)."""
        if type(self).grid_scores_folds is not Predictor.grid_scores_folds:
            return super().grid_scores_folds_retained(
                X, y, w, grid, Xva, _n_classes=_n_classes)
        if not grid:
            return None, None
        import inspect
        kw = {}
        if _n_classes is not None and "_n_classes" in \
                inspect.signature(self.grid_fit_arrays_folds).parameters:
            kw["_n_classes"] = _n_classes
        models = self.grid_fit_arrays_folds(X, y, w, grid, **kw)
        if models is None:
            return None, None
        scores = self.grid_predict_scores_folds(models, Xva)
        if scores is None:
            return None, None
        return scores, models

    def supports_warm_refit(self) -> bool:
        return True

    def refit_winner(self, X, y, w, params, *, warm=None, lane=None,
                     hints=None):
        """Full-data refit warm-started from the fold-AVERAGED layer
        parameters of the winning lane (donated-buffer program). Falls
        back to the cold PRNG init when the refit's layer shapes differ
        from the sweep's (class count shifted between fold and full
        data)."""
        p = {**self.default_params, **self.params, **params}
        if warm is None or lane is None:
            return self.fit_arrays(X, y, w, p), False
        n_classes = max(int(np.asarray(jnp.max(y))) + 1, 2)
        sizes = (int(X.shape[1]),) + tuple(int(x) for x in p["layers"]) \
            + (n_classes,)
        expect = [(sizes[i], sizes[i + 1]) for i in range(len(sizes) - 1)]
        lane_params = [row[int(lane)].params for row in warm]
        if [tuple(np.shape(W)) for W, _ in lane_params[0]] != expect:
            return self.fit_arrays(X, y, w, p), False
        params0 = jax.tree_util.tree_map(
            lambda *xs: jnp.mean(jnp.stack(
                [jnp.asarray(x, jnp.float32) for x in xs]), axis=0),
            *lane_params)
        trained = _mlp_warm_program()(
            X, y, w, params0, max_iter=int(p["max_iter"]),
            step_size=jnp.float32(p["step_size"]))
        return MLPModel(params=[(np.asarray(W), np.asarray(b))
                                for W, b in trained]), True


# ---------------------------------------------------------------------------
# Generalized linear regression
# ---------------------------------------------------------------------------

_FAMILIES = ("gaussian", "binomial", "poisson", "gamma", "tweedie")


def _glm_descent(Xs, y, w, wsum, params0, *, family: str, max_iter: int,
                 fit_intercept: bool, reg_param, var_power):
    """Family-NLL Adam descent from an explicit fit-space init (shared by
    the cold ``_train_glm`` and the warm-started winner refit)."""

    def nll(params):
        beta, b0 = params
        eta = Xs @ beta + b0
        if family == "gaussian":
            m = eta
            ll = -0.5 * (y - m) ** 2
        elif family == "binomial":
            ll = y * eta - jnp.logaddexp(0.0, eta)
        elif family == "poisson":
            ll = y * eta - jnp.exp(eta)
        elif family == "tweedie":
            # compound-Poisson quasi-likelihood, log link, 1 < p < 2
            # (Spark GLR tweedie): ll = y*mu^(1-p)/(1-p) - mu^(2-p)/(2-p).
            # Computed as exp(k*eta) directly: materializing mu = exp(eta)
            # first overflows float32 at |eta| ~ 88 and poisons the scan
            # with inf/nan long before these forms do
            ll = (y * jnp.exp((1.0 - var_power) * eta) / (1.0 - var_power)
                  - jnp.exp((2.0 - var_power) * eta) / (2.0 - var_power))
        else:  # gamma with log link (shape fixed)
            ll = -y * jnp.exp(-eta) - eta
        return -jnp.sum(ll * w) / wsum + reg_param * 0.5 * jnp.sum(beta ** 2)

    opt = optax.adam(0.1)
    state0 = opt.init(params0)

    def step(carry, _):
        params, opt_state = carry
        l, grads = jax.value_and_grad(nll)(params)
        if not fit_intercept:
            grads = (grads[0], jnp.zeros_like(grads[1]))
        updates, opt_state = opt.update(grads, opt_state)
        return (optax.apply_updates(params, updates), opt_state), l

    (params, _), _ = jax.lax.scan(step, (params0, state0), None,
                                  length=max_iter)
    return params


def _glm_fit_space(X, w):
    wsum = jnp.maximum(jnp.sum(w), 1.0)
    mu = jnp.sum(X * w[:, None], axis=0) / wsum
    sd = jnp.sqrt(jnp.maximum(
        jnp.sum(((X - mu) ** 2) * w[:, None], axis=0) / wsum, 1e-12))
    return (X - mu) / sd, mu, sd, wsum


@functools.partial(jax.jit, static_argnames=("family", "max_iter",
                                             "fit_intercept"))
def _train_glm(X, y, w, *, family: str, max_iter: int, fit_intercept: bool,
               reg_param, var_power=jnp.float32(1.5)):
    d = X.shape[1]
    Xs, mu, sd, wsum = _glm_fit_space(X, w)
    params0 = (jnp.zeros(d, jnp.float32), jnp.float32(0.0))
    beta, b0 = _glm_descent(Xs, y, w, wsum, params0, family=family,
                            max_iter=max_iter, fit_intercept=fit_intercept,
                            reg_param=reg_param, var_power=var_power)
    beta_orig = beta / sd
    b_orig = b0 - jnp.sum(beta * mu / sd)
    return beta_orig, b_orig


def _train_glm_from(X, y, w, beta_init, b_init, *, family: str,
                    max_iter: int, fit_intercept: bool, reg_param,
                    var_power):
    """Warm-started GLM refit (round 9): init given in ORIGINAL feature
    space (the fold-back space the stacked sweep parameters live in),
    mapped into the refit data's own standardized space."""
    Xs, mu, sd, wsum = _glm_fit_space(X, w)
    params0 = (beta_init * sd, b_init + mu @ beta_init)
    beta, b0 = _glm_descent(Xs, y, w, wsum, params0, family=family,
                            max_iter=max_iter, fit_intercept=fit_intercept,
                            reg_param=reg_param, var_power=var_power)
    beta_orig = beta / sd
    b_orig = b0 - jnp.sum(beta * mu / sd)
    return beta_orig, b_orig


_GLM_WARM = None


def _glm_warm_program():
    """Donated-buffer compiled warm GLM refit (argnums 3/4 = the init
    arrays, consumed exactly once)."""
    global _GLM_WARM
    if _GLM_WARM is None:
        from transmogrifai_tpu.models.base import compile_refit
        _GLM_WARM = compile_refit(
            _train_glm_from, donate_argnums=(3, 4),
            static_argnames=("family", "max_iter", "fit_intercept"))
    return _GLM_WARM


class GLMModel(PredictionModel):
    def __init__(self, weights=None, intercept=0.0,
                 family: str = "gaussian", uid: Optional[str] = None):
        # may be device arrays during the CV sweep (no host pull);
        # conversion happens lazily on serialization/introspection
        self.weights = weights if weights is not None else np.zeros(0)
        self.intercept = intercept
        self.family = family
        super().__init__(uid=uid)

    def device_params(self):
        return (jnp.asarray(self.weights, jnp.float32),
                jnp.asarray(self.intercept, jnp.float32))

    def quantize_device_params(self, precision):
        if precision != "int8":
            return None
        from transmogrifai_tpu.utils.precision import quantize_weights
        W, b = self.device_params()
        return (quantize_weights(W), b)

    def device_apply(self, params, col: fr.VectorColumn) -> fr.PredictionColumn:
        W, b = params
        eta = col.values @ W + b
        if self.family == "gaussian":
            mean = eta
        elif self.family == "binomial":
            mean = jax.nn.sigmoid(eta)
        else:
            mean = jnp.exp(eta)
        n = mean.shape[0]
        empty = jnp.zeros((n, 0), jnp.float32)
        return fr.PredictionColumn(mean, empty, empty)

    def fitted_state(self):
        return {"weights": np.asarray(self.weights, np.float64),
                "intercept": np.float64(self.intercept)}

    def set_fitted_state(self, state):
        self.weights = np.asarray(state["weights"], np.float64)
        self.intercept = float(state["intercept"])

    def config(self):
        return {"family": self.family}

    @classmethod
    def from_config(cls, config, uid=None):
        return cls(family=config.get("family", "gaussian"), uid=uid)

    def feature_contributions(self):
        return np.asarray(self.weights)


class OpGeneralizedLinearRegression(Predictor):
    default_params = {"family": "gaussian", "reg_param": 0.0,
                      "max_iter": 300, "fit_intercept": True,
                      "variance_power": 1.5}

    def fit_arrays(self, X, y, w, params):
        p = {**self.default_params, **params}
        family = p["family"]
        if family not in _FAMILIES:
            raise ValueError(f"Unknown GLM family {family!r}")
        vp = float(p["variance_power"])
        if family == "tweedie" and not 1.0 < vp < 2.0:
            raise ValueError(
                f"tweedie variance_power must be in (1, 2), got {vp}")
        beta, b0 = _train_glm(X, y, w, family=family,
                              max_iter=int(p["max_iter"]),
                              fit_intercept=bool(p["fit_intercept"]),
                              reg_param=jnp.float32(p["reg_param"]),
                              var_power=jnp.float32(vp))
        return GLMModel(weights=np.asarray(beta), intercept=float(b0),
                        family=family)

    def grid_predict_scores(self, models, X):
        """[G, n] mean predictions through the family link (None when grid
        points mix families) — keeps both sweep paths on one metric
        program."""
        folds = self.grid_predict_scores_folds([models], X[None])
        return None if folds is None else folds[0]

    # -- fold-stacked sweep --------------------------------------------------
    def grid_fit_arrays_folds(self, X, y, w, grid):
        """Fold-stacked GLM sweep: reg_param/variance_power are the traced
        grid axes, one vmap-of-vmap program per distinct (family, max_iter,
        fit_intercept) combo; fitted params stay device views."""
        if not grid:
            return []
        merged = [{**self.default_params, **self.params, **g} for g in grid]
        for p in merged:
            if p["family"] not in _FAMILIES:
                raise ValueError(f"Unknown GLM family {p['family']!r}")
            vp = float(p["variance_power"])
            if p["family"] == "tweedie" and not 1.0 < vp < 2.0:
                raise ValueError(
                    f"tweedie variance_power must be in (1, 2), got {vp}")
        k = int(X.shape[0])
        models: list[list] = [[None] * len(grid) for _ in range(k)]
        by_kw: dict[tuple, list[int]] = {}
        for i, p in enumerate(merged):
            by_kw.setdefault((p["family"], int(p["max_iter"]),
                              bool(p["fit_intercept"])), []).append(i)
        for (family, mi, fi), idxs in by_kw.items():
            rp = jnp.asarray([float(merged[i]["reg_param"]) for i in idxs],
                             jnp.float32)
            vp = jnp.asarray([float(merged[i]["variance_power"])
                              for i in idxs], jnp.float32)
            inner = lambda Xk, yk, wk, _f=family, _m=mi, _i=fi: jax.vmap(  # noqa: E731,E501
                lambda r, v: _train_glm(Xk, yk, wk, family=_f, max_iter=_m,
                                        fit_intercept=_i, reg_param=r,
                                        var_power=v))(rp, vp)
            betas, b0s = jax.vmap(inner)(X, y, w)  # [k, g, d], [k, g]
            for f in range(k):
                for j, i in enumerate(idxs):
                    models[f][i] = GLMModel(weights=betas[f, j],
                                            intercept=b0s[f, j],
                                            family=family)
        return models

    def grid_predict_scores_folds(self, models, X):
        """[k, G, n_va] mean predictions through the family link (None when
        grid points mix families — their links differ)."""
        if not models or not models[0]:
            return None
        fams = {m.family for row in models for m in row}
        if len(fams) != 1:
            return None
        family = fams.pop()
        W = jnp.stack([jnp.stack([jnp.asarray(m.weights, jnp.float32)
                                  for m in row]) for row in models])
        b = jnp.stack([jnp.stack([jnp.asarray(m.intercept, jnp.float32)
                                  for m in row]) for row in models])
        eta = jnp.einsum("knd,kgd->kgn", X, W) + b[:, :, None]
        if family == "gaussian":
            return eta
        if family == "binomial":
            return jax.nn.sigmoid(eta)
        return jnp.exp(eta)

    def grid_scores_folds_retained(self, X, y, w, grid, Xva,
                                   _n_classes=None):
        """One-sync dispatch unit: stacked scores plus the ``[k][G]``
        fitted-model nest retained as the warm-refit handle (model
        weights are device views of the stacked result). A subclass
        overriding ``grid_scores_folds`` keeps its semantics (delegate,
        no warm handle)."""
        if type(self).grid_scores_folds is not Predictor.grid_scores_folds:
            return super().grid_scores_folds_retained(
                X, y, w, grid, Xva, _n_classes=_n_classes)
        if not grid:
            return None, None
        models = self.grid_fit_arrays_folds(X, y, w, grid)
        if models is None:
            return None, None
        scores = self.grid_predict_scores_folds(models, Xva)
        if scores is None:
            return None, None
        return scores, models

    def supports_warm_refit(self) -> bool:
        return True

    def refit_winner(self, X, y, w, params, *, warm=None, lane=None,
                     hints=None):
        """Full-data refit warm-started from the fold-AVERAGED winning-
        lane coefficients through the donated-buffer program; cold
        ``fit_arrays`` (the serial path, bitwise) without a handle."""
        p = {**self.default_params, **self.params, **params}
        if warm is None or lane is None:
            return self.fit_arrays(X, y, w, p), False
        family = p["family"]
        if family not in _FAMILIES:
            raise ValueError(f"Unknown GLM family {family!r}")
        vp = float(p["variance_power"])
        if family == "tweedie" and not 1.0 < vp < 2.0:
            raise ValueError(
                f"tweedie variance_power must be in (1, 2), got {vp}")
        lane_models = [row[int(lane)] for row in warm]
        beta_init = jnp.mean(jnp.stack(
            [jnp.asarray(m.weights, jnp.float32) for m in lane_models]),
            axis=0)
        b_init = jnp.mean(jnp.stack(
            [jnp.asarray(m.intercept, jnp.float32) for m in lane_models]))
        beta, b0 = _glm_warm_program()(
            X, y, w, beta_init, b_init, family=family,
            max_iter=int(p["max_iter"]),
            fit_intercept=bool(p["fit_intercept"]),
            reg_param=jnp.float32(p["reg_param"]),
            var_power=jnp.float32(vp))
        return GLMModel(weights=np.asarray(beta), intercept=float(b0),
                        family=family), True


# ---------------------------------------------------------------------------
# Isotonic calibration
# ---------------------------------------------------------------------------

def _pav(x: np.ndarray, y: np.ndarray, w: np.ndarray
         ) -> tuple[np.ndarray, np.ndarray]:
    """Pool-adjacent-violators on (sorted-x, y, w); returns (x_knots, y_knots)."""
    order = np.argsort(x, kind="stable")
    xs, ys, ws = x[order], y[order].astype(float), w[order].astype(float)
    # pool
    vals, wts, xs_list = [], [], []
    for xi, yi, wi in zip(xs, ys, ws):
        vals.append(yi)
        wts.append(wi)
        xs_list.append(xi)
        while len(vals) > 1 and vals[-2] > vals[-1]:
            y2, w2 = vals.pop(), wts.pop()
            y1, w1 = vals.pop(), wts.pop()
            xs_list.pop()
            vals.append((y1 * w1 + y2 * w2) / (w1 + w2))
            wts.append(w1 + w2)
        # keep the x of the last element of each pool
    return np.asarray(xs_list[:len(vals)]), np.asarray(vals)


class IsotonicCalibratorModel(PredictionModel):
    """Calibrates the positive-class probability with the fitted isotonic
    step function (linear interpolation between knots)."""

    def __init__(self, x_knots=None, y_knots=None, uid: Optional[str] = None):
        self.x_knots = np.asarray(x_knots, np.float64) \
            if x_knots is not None else np.zeros(1)
        self.y_knots = np.asarray(y_knots, np.float64) \
            if y_knots is not None else np.zeros(1)
        super().__init__(uid=uid)

    def device_params(self):
        return (jnp.asarray(self.x_knots, jnp.float32),
                jnp.asarray(self.y_knots, jnp.float32))

    def device_apply(self, params, col: fr.PredictionColumn
                     ) -> fr.PredictionColumn:
        xk, yk = params
        score = col.probability[:, 1] if col.probability.shape[1] >= 2 \
            else col.prediction
        cal = jnp.interp(score, xk, yk)
        prob = jnp.stack([1.0 - cal, cal], axis=1)
        pred = (cal >= 0.5).astype(jnp.float32)
        return fr.PredictionColumn(pred, col.raw_prediction, prob)

    def transform_row(self, *values):
        pm = values[-1]
        score = pm.get("probability_1", pm.get("prediction", 0.0))
        cal = float(np.interp(score, self.x_knots, self.y_knots))
        return ft.Prediction.make(
            1.0 if cal >= 0.5 else 0.0,
            raw_prediction=pm_raw(pm), probability=[1.0 - cal, cal]).value

    def fitted_state(self):
        return {"x_knots": self.x_knots, "y_knots": self.y_knots}

    def set_fitted_state(self, state):
        self.x_knots = np.asarray(state["x_knots"], np.float64)
        self.y_knots = np.asarray(state["y_knots"], np.float64)

    def config(self):
        return {}

    @classmethod
    def from_config(cls, config, uid=None):
        return cls(uid=uid)


def pm_raw(pm: dict) -> list:
    out = []
    i = 0
    while f"rawPrediction_{i}" in pm:
        out.append(pm[f"rawPrediction_{i}"])
        i += 1
    return out


class IsotonicRegressionCalibrator(Estimator):
    """(label RealNN, Prediction) -> calibrated Prediction (reference
    ``IsotonicRegressionCalibrator`` wrapping Spark IsotonicRegression)."""

    in_types = (ft.RealNN, ft.Prediction)
    out_type = ft.Prediction

    def __init__(self, uid: Optional[str] = None):
        super().__init__(uid=uid)

    def fit_model(self, data):
        label_name, pred_name = self.input_names
        y = np.asarray(data.device_col(label_name).values, np.float64)
        pred_col = data.device_col(pred_name)
        prob = np.asarray(pred_col.probability)
        score = prob[:, 1] if prob.ndim == 2 and prob.shape[1] >= 2 \
            else np.asarray(pred_col.prediction)
        xk, yk = _pav(score, y, np.ones_like(y))
        return IsotonicCalibratorModel(x_knots=xk, y_knots=yk)
