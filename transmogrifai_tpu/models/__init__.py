from transmogrifai_tpu.models.base import PredictionModel, Predictor

__all__ = ["PredictionModel", "Predictor"]
