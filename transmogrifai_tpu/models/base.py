"""Predictor/PredictionModel bases.

Parity: reference ``core/.../stages/sparkwrappers/specific/OpPredictorWrapper
.scala:70-153`` and the OP model wrappers (`OpLogisticRegression` etc.) —
every model is an Estimator of (response RealNN, features OPVector) ->
Prediction, whose fitted form is a Transformer exposing row-level scoring.

TPU-first: instead of wrapping an external engine, each model family
implements ``fit_arrays(X, y, w, params)`` as pure JAX and, when the math
allows, ``grid_fit_arrays`` training the entire hyperparameter grid as one
stacked ``vmap``/sharded program (the ModelSelector's sweep axis — reference
P3 thread-pool parallelism becomes a batched leading axis).
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from transmogrifai_tpu import frame as fr
from transmogrifai_tpu.stages.base import (
    AllowLabelAsInput, DeviceTransformer, Estimator,
)
from transmogrifai_tpu.types import feature_types as ft

__all__ = ["Predictor", "PredictionModel", "supports_fold_stacking",
           "supports_tree_stacking", "compile_refit"]


def compile_refit(fn, *, donate_argnums: tuple[int, ...] = (),
                  static_argnames: tuple[str, ...] = ()):
    """Compile a warm-refit program with its initial-parameter buffers
    DONATED (round 9): the stacked fold parameters feeding the winner's
    warm start are dead after the refit consumes them, so donation lets
    XLA reuse their device storage for the refit's own parameter arrays
    in place instead of holding both copies live. Donation is a no-op
    (and a warning) on backends without buffer aliasing — plain CPU — so
    it is applied only where the runtime honors it."""
    import jax
    donate = donate_argnums if jax.default_backend() != "cpu" else ()
    return jax.jit(fn, donate_argnums=donate,
                   static_argnames=static_argnames)


class Predictor(Estimator):
    """Base estimator for (label, features) -> Prediction models."""

    in_types = (ft.RealNN, ft.OPVector)
    out_type = ft.Prediction

    #: hyperparameters exposed to grid search, with defaults
    default_params: dict[str, Any] = {}

    def __init__(self, uid: Optional[str] = None, **params):
        unknown = set(params) - set(self.default_params)
        if unknown:
            raise ValueError(f"{type(self).__name__}: unknown params {unknown}")
        self.params = {**self.default_params, **params}
        super().__init__(uid=uid)

    def config(self) -> dict:
        return dict(self.params)

    @classmethod
    def from_config(cls, config: dict, uid: Optional[str] = None):
        return cls(uid=uid, **config)

    # -- data plumbing -------------------------------------------------------
    def _xyw(self, data) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
        label_name, feat_name = self.input_names
        y_col = data.device_col(label_name)
        x_col = data.device_col(feat_name)
        w = getattr(data, "sample_weight", None)
        if w is None:
            w = jnp.ones_like(y_col.values)
        return x_col.values, y_col.values, w

    # -- model-family contract ----------------------------------------------
    def fit_arrays(self, X, y, w, params: dict) -> "PredictionModel":
        raise NotImplementedError

    def grid_fit_arrays(self, X, y, w, grid: Sequence[dict]
                        ) -> list["PredictionModel"]:
        """Train every grid point. Default: sequential; vmappable families
        override with a stacked-axis batched trainer."""
        return [self.fit_arrays(X, y, w, {**self.params, **g}) for g in grid]

    def grid_predict_scores(self, models: Sequence["PredictionModel"], X):
        """Fast sweep path: validation scores for all fitted grid models as
        one [G, n] device array (margins for binary, predictions for
        regression), or None when the family has no batched path — the
        selector then falls back to per-model evaluation."""
        return None

    # -- fold-stacked sweep contract -----------------------------------------
    def grid_fit_arrays_folds(self, X, y, w, grid: Sequence[dict]
                              ) -> Optional[list]:
        """Fold-stacked trainer: the CV sweep's fast path. ``X: [k, n, d]``,
        ``y/w: [k, n]`` carry a leading fold axis (``OpCrossValidation``
        guarantees equal fold shapes precisely so this axis exists); a
        vmappable family trains all k folds x |grid| points as ONE compiled
        program and returns a ``[k][G]`` nested list of fitted models whose
        parameters stay device-resident (no host pull inside the sweep).

        Default: ``None`` — family has no fold axis; the selector falls back
        to its per-fold loop. Families opt in by overriding; the selector's
        eligibility check (``supports_fold_stacking``) additionally refuses
        the stacked path for subclasses that override the per-fold trainers
        below the opt-in, so custom ``fit_arrays``/``grid_fit_arrays``
        semantics are never silently bypassed."""
        return None

    def grid_predict_scores_folds(self, models: Sequence[Sequence[
            "PredictionModel"]], X):
        """Fold-stacked scoring: ``models`` is the ``[k][G]`` nest from
        ``grid_fit_arrays_folds``, ``X: [k, n_va, d]`` the stacked
        validation folds; returns one ``[k, G, n_va]`` device score array
        (margins for binary, predictions for regression) or None when no
        batched scalar score exists (e.g. multiclass)."""
        return None

    def fold_stack_unit_width(self, grid: Sequence[dict]) -> int:
        """Per-row, per-grid-lane f32 lane count the fold-stacked trainer
        keeps live (logits/scores/residuals) — the selector's HBM guard
        multiplies this by k x G x rows. Default 4 covers the linear/GLM/NB
        families (<= 2 classes + gradients); families with wider per-row
        intermediates (hidden activations) override."""
        return 4

    def grid_scores_folds(self, X, y, w, grid: Sequence[dict], Xva,
                          _n_classes: Optional[int] = None):
        """One-call fold-stacked train+score — what the selector's fast
        path actually invokes. Default composes the two contract methods;
        families with a fully-stacked trainer override to go straight from
        stacked parameters to stacked scores, skipping the per-(fold, grid)
        model materialization round trip entirely (the sweep discards the
        models anyway — the winner refits later). Returns ``[k, G, n_va]``
        scores or None when the family can't serve the stacked path.
        ``_n_classes`` threads the selector's once-per-sweep class count
        to stacked trainers that accept it (signature-gated so custom
        overrides with the old arity keep working)."""
        import inspect
        kw = {}
        if _n_classes is not None and "_n_classes" in \
                inspect.signature(self.grid_fit_arrays_folds).parameters:
            kw["_n_classes"] = _n_classes
        models = self.grid_fit_arrays_folds(X, y, w, grid, **kw)
        if models is None:
            return None
        return self.grid_predict_scores_folds(models, Xva)

    def grid_scores_folds_retained(self, X, y, w, grid: Sequence[dict],
                                   Xva, _n_classes: Optional[int] = None):
        """One-sync sweep dispatch unit (round 9): like
        ``grid_scores_folds`` but additionally returns an opaque
        warm-start handle — the family's stacked fold parameters, kept
        device-resident so the winner refit can initialize from them
        (``refit_winner``) — as ``(scores, warm)``. ``warm`` is ``None``
        when the family has nothing reusable (closed-form fits, custom
        overrides). ``_n_classes`` threads the selector's once-per-sweep
        label-class count so the dispatch phase issues no per-family
        blocking device pull; families whose stacked trainers accept it
        receive it, others compute their own (the pre-round-9 behavior).

        Default: delegate to ``grid_scores_folds`` (honoring subclass
        overrides of it) with no warm handle."""
        import inspect
        kw = {}
        if _n_classes is not None and "_n_classes" in \
                inspect.signature(self.grid_scores_folds).parameters:
            kw["_n_classes"] = _n_classes
        return self.grid_scores_folds(X, y, w, grid, Xva, **kw), None

    # -- winner refit (round 9) ----------------------------------------------
    def refit_winner(self, X, y, w, params: dict, *, warm=None,
                     lane: Optional[int] = None, hints: Optional[dict] = None
                     ) -> tuple["PredictionModel", bool]:
        """Refit the sweep winner on the full prepared training data.
        ``warm`` is the handle ``grid_scores_folds_retained`` returned for
        this family (stacked fold parameters), ``lane`` the winning grid
        index into it, ``hints`` selector-provided reuse state (trees: the
        dataset-level ``bin_plans``). Returns ``(model, warm_used)`` —
        families that can initialize from the fold parameters (or skip
        recomputing sweep byproducts) override; the default is the exact
        cold refit the serial path always ran, so refit results without an
        override stay bitwise-identical."""
        return self.fit_arrays(X, y, w, params), False

    def supports_warm_refit(self) -> bool:
        """True when ``refit_winner`` can actually use a ``warm`` handle —
        the selector retains a family's stacked fold parameters past the
        sweep ONLY then (holding them until the refit costs HBM, so
        families with cold refits must not opt in)."""
        return False

    def fit_model(self, data) -> "PredictionModel":
        X, y, w = self._xyw(data)
        return self.fit_arrays(X, y, w, self.params)


def _stacking_safe(est: Predictor, opt_in: tuple[str, ...],
                   guarded: tuple[str, ...]) -> bool:
    """Shared capability rule for both stacking contracts: the family
    defined one of the ``opt_in`` methods somewhere below ``Predictor``
    (opted in), AND no subclass overrides any of the ``guarded`` per-fold
    trainers/scorers *more derived than* that opt-in in the MRO — a test
    double or wrapper that redefines them (counting fits, injecting
    failures, changing the math) must keep its semantics, so the sweep
    routes such families through the per-fold loop where the override is
    actually called."""
    mro = type(est).__mro__
    owner_i = min((i for i, c in enumerate(mro) if c is not Predictor
                   and any(n in vars(c) for n in opt_in)), default=None)
    if owner_i is None:
        return False  # never opted in (base default = no stacked axis)
    for name in guarded:
        def_i = next((i for i, c in enumerate(mro) if name in vars(c)), None)
        if def_i is not None and def_i < owner_i:
            return False  # more-derived per-fold override would be bypassed
    return True


def supports_fold_stacking(est: Predictor) -> bool:
    """True when the estimator's fold-stacked trainer
    (``grid_fit_arrays_folds``/``grid_scores_folds``) is safe to use in
    place of its per-fold one (see ``_stacking_safe``)."""
    return _stacking_safe(
        est,
        ("grid_fit_arrays_folds", "grid_scores_folds",
         "_fold_stacked_params"),
        ("grid_fit_arrays", "fit_arrays", "grid_predict_scores",
         "grid_predict_scores_folds"))


def supports_tree_stacking(est: Predictor) -> bool:
    """True when the estimator's fold x grid-stacked TREE trainer
    (``tree_stack_scores`` + ``tree_stack_groups``, opted in by
    ``models.trees._TreePredictor``) is safe to use in place of its
    per-fold loop. Same override discipline as ``supports_fold_stacking``:
    subclasses redefining the per-fold trainers below the opt-in (e.g.
    ``OpDecisionTree*``, which mutate ``bootstrap`` inside a custom
    ``fit_arrays``) keep the loop where their semantics run."""
    return _stacking_safe(
        est,
        ("tree_stack_scores", "tree_stack_groups"),
        ("grid_fit_arrays", "fit_arrays", "grid_predict_scores"))


class PredictionModel(AllowLabelAsInput, DeviceTransformer):
    """Fitted model: consumes only the features vector at transform time.

    ``AllowLabelAsInput``: the optional leading label input exists for
    lineage/naming parity only — ``runtime_input_names`` excludes it, so
    wiring a fitted/imported model directly under a workflow (the MLeap
    serving analog) is not label leakage."""

    in_types = (ft.RealNN, ft.OPVector)
    out_type = ft.Prediction

    def runtime_input_names(self) -> tuple[str, ...]:
        return (self.input_names[1],) if len(self.input_names) == 2 \
            else self.input_names

    def validate_inputs(self, features) -> None:
        super().validate_inputs(features)
        # the AllowLabelAsInput exemption covers ONLY the designated label
        # slot (0): a response-DERIVED features vector is still leakage
        feat_slots = features[1:] if len(features) >= 2 else features
        bad = [f.name for f in feat_slots if f.is_response]
        if bad:
            raise ValueError(
                f"{self}: response-derived feature(s) {bad} cannot feed "
                "the model's FEATURES slot (label leakage); only the "
                "leading label input may be a response")

    # device_apply(params, features_col) -> PredictionColumn
    def predict_arrays(self, X) -> fr.PredictionColumn:
        """One JITTED apply. In the fused layer program this path is
        already compiled; here (sweep fallback scoring, LOCO, row path) an
        eager device_apply would dispatch every primitive separately —
        for tree ensembles that is thousands of eager gathers per call.

        The cache keys on ``config()``: device_apply bakes structural
        Python attributes (probabilistic/family/kind/...) into the trace,
        and those may change via ``set_fitted_state`` after a first
        predict — a stale trace would silently keep the OLD semantics."""
        cfg = self.config()
        cached = self.__dict__.get("_jit_apply")
        if cached is None or cached[0] != cfg:
            cached = (cfg, jax.jit(lambda p, c: self.device_apply(p, c)))
            self.__dict__["_jit_apply"] = cached
        return cached[1](self.device_params(), fr.VectorColumn(X))

    def transform_row(self, *values):
        """Row path: last value is the feature vector (label may be absent)."""
        x = np.asarray(values[-1], dtype=np.float32)[None, :]
        out = self.predict_arrays(jnp.asarray(x))
        return ft.Prediction.make(
            float(out.prediction[0]),
            np.asarray(out.raw_prediction[0]),
            np.asarray(out.probability[0])).value
