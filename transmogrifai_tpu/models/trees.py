"""Histogram-based tree ensembles: GBT / XGBoost-parity boosting + random
forests + single decision trees — pure JAX, TPU-native.

Parity targets: reference ``OpXGBoostClassifier/Regressor`` (xgboost4j JNI ->
native libxgboost histogram boosting), ``OpGBTClassifier/Regressor``,
``OpRandomForestClassifier/Regressor``, ``OpDecisionTreeClassifier/Regressor``
(Spark MLlib executor-distributed histogram trees). This module replaces both
native engines with one device-resident histogram learner (SURVEY §2.7 P5):

- features quantile-bin once into int32 codes (``max_bins``, default 64)
- each tree level builds ALL (node, feature, bin) gradient/hessian
  histograms with one of two engines (``hist=``): the GSPMD-safe
  scatter-add over the row-sharded binned matrix (the analog of XGBoost's
  Rabit all-reduced per-worker histograms; under a mesh the scatter runs
  per shard and the histogram psum rides ICI) or — the single-chip hot
  path — the SORTED engine: rows kept grouped by node across levels,
  node segments padded to block multiples, and the whole level computed
  as blocked one-hot MXU contractions whose cost is independent of the
  node count (host-fenced on chip: 5-7x faster per tree at 1M rows,
  scripts/tpu_calibrate3.py + scripts/tpu_sorted_vs_scatter.py)
- split choice is the XGBoost gain formula (lambda/gamma/min_child_weight)
  via cumulative sums along the bin axis; the whole ensemble trains inside
  one ``lax.scan`` jitted program (boosting) or a scanned loop of
  independent bootstrapped trees (forest)
- the CV sweep stacks further (round 8): ``train_score_stacked`` vmaps
  the grower over a leading (fold x grid-lane) batch — one compiled
  program trains and scores a whole depth-group of the ModelSelector's
  k-fold x hyperparameter sweep, per-lane scalars riding as batched
  operands and the scatter histograms folding every batch axis into the
  node axis (``ops/histograms.py``'s custom_vmap rule)
- trees are fixed-shape: a non-splitting node stores feature -1 and routes
  rows left, so depth-d trees are dense arrays and prediction is d gathers.

Random forests grow CART-style regression trees on bootstrap (Poisson)
weights with per-tree feature subsampling; for classification the leaf holds
the class-probability estimate (variance-reduction splits ~ gini for binary).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from transmogrifai_tpu import frame as fr
from transmogrifai_tpu.models.base import PredictionModel, Predictor

__all__ = [
    "OpGBTClassifier", "OpGBTRegressor",
    "OpXGBoostClassifier", "OpXGBoostRegressor",
    "OpRandomForestClassifier", "OpRandomForestRegressor",
    "OpDecisionTreeClassifier", "OpDecisionTreeRegressor",
    "TreeEnsembleModel",
]


# ---------------------------------------------------------------------------
# binning
# ---------------------------------------------------------------------------

#: rows used for quantile-edge estimation; above this the percentiles run on
#: a deterministic subsample (XGBoost's approx-sketch analog — edge jitter of
#: O(1/sqrt(sample)) is far below bin width at 64 bins)
_EDGE_SAMPLE_CAP = 2_000_000


def quantile_bin_edges(X: np.ndarray, max_bins: int,
                       seed: int = 0) -> np.ndarray:
    """[d, max_bins-1] quantile edges per feature (host, once per fit)."""
    if X.shape[0] > _EDGE_SAMPLE_CAP:
        idx = np.random.default_rng(seed).choice(
            X.shape[0], size=_EDGE_SAMPLE_CAP, replace=False)
        X = X[np.sort(idx)]
    qs = np.linspace(0, 100, max_bins + 1)[1:-1]
    edges = np.percentile(X, qs, axis=0).T  # [d, B-1]
    return np.ascontiguousarray(edges, dtype=np.float32)


@functools.partial(jax.jit, static_argnames=("max_bins",))
def quantile_bin_edges_device(X, *, max_bins: int):
    """[d, max_bins-1] quantile edges computed ON DEVICE (one jitted sort
    per fit). The host path pulls the full X matrix over the host<->device
    link first — at 1M x 28 that is ~100MB through a tunneled TPU per grid
    point; this keeps the whole binning pass device-resident."""
    qs = jnp.linspace(0.0, 1.0, max_bins + 1)[1:-1]
    return jnp.quantile(X, qs, axis=0).T.astype(jnp.float32)


@jax.jit
def bin_data(X, edges):
    """Bin values: [n, d] int32 in [0, B-1] via vectorized searchsorted."""
    def per_feature(x_col, e_col):
        return jnp.searchsorted(e_col, x_col, side="left")
    return jax.vmap(per_feature, in_axes=(1, 1), out_axes=1)(
        X, edges.T.astype(X.dtype)).astype(jnp.int32)


# ---------------------------------------------------------------------------
# single-tree growth (one jitted program per (n, d, depth, B) shape)
# ---------------------------------------------------------------------------

def _hist_mode_for(Xb) -> str:
    """Static histogram-engine choice for a fit: the sorted MXU path for
    large TPU fits (on-chip shootout: ~7x/level at 1M rows,
    scripts/tpu_calibrate3.py) — single-shard directly, mesh-sharded via
    the explicit shard_map wrapper (``train_ensemble_sharded``) — and
    the scatter path for small fits and for sharded inputs without a
    mesh context (whose per-shard scatters GSPMD all-reduces; the sorted
    path's global-index bookkeeping would generate heavy cross-shard
    collectives under plain GSPMD). Overridable via
    TRANSMOGRIFAI_TREE_HIST."""
    import os
    forced = os.environ.get("TRANSMOGRIFAI_TREE_HIST")
    if forced and forced not in ("scatter", "sorted"):
        raise ValueError(
            f"TRANSMOGRIFAI_TREE_HIST={forced!r}: expected 'scatter' "
            "or 'sorted'")
    if forced == "scatter":
        return "scatter"
    try:
        single = len(Xb.devices()) == 1
    except Exception:  # failure-ok: device probe; default to single-device route
        single = True

    def sharded_route() -> tuple[str, str]:
        # multi-device input: the sorted engine needs the explicit
        # shard_map wrapper, which requires an active mesh and a row
        # count divisible by the data axis (what shard_training_rows
        # produces); anything else keeps the GSPMD scatter path, which
        # accepts replicated/unevenly-sharded inputs. Returns
        # (route, downgrade reason or "").
        from transmogrifai_tpu.parallel.mesh import current_mesh
        ctx = current_mesh()
        if ctx is None:
            return "scatter", "multi-device input but no active mesh context"
        if Xb.shape[0] % ctx.n_data:
            return "scatter", (
                f"row count {int(Xb.shape[0])} not divisible by the mesh "
                f"data axis ({ctx.n_data})")
        return "sorted_sharded", ""

    if forced == "sorted":
        if single:
            return "sorted"
        route, why = sharded_route()
        if route == "scatter":
            # a forced engine that silently downgrades poisons A/B reruns —
            # the measurement would time the WRONG engine (ADVICE r5). Loud
            # by default; TRANSMOGRIFAI_TREE_HIST_STRICT=1 makes it fatal.
            import warnings
            msg = (f"TRANSMOGRIFAI_TREE_HIST=sorted downgraded to "
                   f"'scatter': {why}. Shard the rows via "
                   "shard_training_rows under an active mesh to keep the "
                   "sorted engine.")
            if os.environ.get("TRANSMOGRIFAI_TREE_HIST_STRICT") == "1":
                raise RuntimeError(msg)
            warnings.warn(msg, RuntimeWarning)
        return route
    # auto-select only on TPU: the einsum path trades ~B-times more
    # (MXU-friendly) FLOPs for the serialized scatter, a trade validated
    # on-chip; CPU/GPU keep the scatter path unless forced
    if Xb.shape[0] >= _SORT_MIN_ROWS and jax.default_backend() == "tpu":
        return "sorted" if single else sharded_route()[0]
    return "scatter"


#: histogram node budget per materialized array: [nodes, d, B] f32 x2 (g, h).
#: At the default (1024, d=28, B=64) that is ~14 MB; levels with more nodes
#: compute best-splits chunk-by-chunk so HBM stays bounded at any depth.
_MAX_HIST_NODES = 1024

#: sorted-histogram path: rows per MXU contraction block. Host-fenced chip
#: measurements (scripts/tpu_calibrate3.py, 1M x 28 x 64): the scatter-add
#: histogram costs ~540 ms/level (serialized, ~0.9 GB/s) while the sorted
#: block one-hot contraction runs the same level in ~80 ms and its cost is
#: INDEPENDENT of the node count, so deep levels stop needing chunking.
_SORT_BLOCK = 256
#: byte budget for the materialized one-hot chunk ([blocks, C, d, B] bf16)
_SORT_OH_BUDGET = 192 * 1024 * 1024
#: row threshold above which single-device fits switch to the sorted path
#: (below it the scatter path's lower fixed cost wins and stays the
#: well-trodden mesh/GSPMD route)
_SORT_MIN_ROWS = 150_000


def _pow2_at_most(x: int) -> int:
    return 1 << (max(int(x), 1).bit_length() - 1)


def _sorted_engine_default() -> str:
    """Histogram contraction engine for the sorted path. The XLA einsum
    is the measured winner ON CHIP (1M x 28 x 64, host-fenced: einsum
    440/1300 ms for d6/d12 trees vs 521/1502 ms for the fused Pallas
    kernel — per-grid-step overhead of 28 small dots x ~4k blocks beats
    the one-hot HBM traffic it saves), so it is the default everywhere;
    TRANSMOGRIFAI_SORTED_HIST=pallas opts into the kernel (A/B reruns).

    Consulted ONCE per fit at Python level (fit_arrays) and threaded as
    a STATIC argument — never read inside a traced function, where the
    jit cache would silently pin the first value seen."""
    import os
    forced = os.environ.get("TRANSMOGRIFAI_SORTED_HIST")
    if forced:
        if forced not in ("einsum", "pallas"):
            raise ValueError(
                f"TRANSMOGRIFAI_SORTED_HIST={forced!r}: expected 'einsum' "
                "or 'pallas'")
        return forced
    return "einsum"


def _sorted_acc_default() -> str:
    """Accumulation dtype policy for the sorted path's one-hot histogram
    contraction. ``"auto"`` (default) keeps the measured TPU choice — bf16
    one-hot with f32 ``preferred_element_type`` accumulation on chip, f32
    everywhere else; ``TRANSMOGRIFAI_SORTED_ACC=f32`` forces full-f32
    operands (the escape hatch when bf16 bin-code/stat rounding is
    suspected in split decisions — A/B rerun knob, ADVICE r5), and
    ``=bf16`` forces bf16 operands on any backend (lets a CPU test
    exercise the TPU numerics). Same static-threading discipline as
    ``_sorted_engine_default``: consulted once per fit at Python level."""
    import os
    forced = os.environ.get("TRANSMOGRIFAI_SORTED_ACC")
    if forced:
        if forced not in ("auto", "f32", "bf16"):
            raise ValueError(
                f"TRANSMOGRIFAI_SORTED_ACC={forced!r}: expected 'auto', "
                "'f32' or 'bf16'")
        return forced
    return "auto"


def _sorted_layout(counts, n: int, C: int):
    """Padded block layout for rows grouped by node.

    ``counts``: [N] rows per node (sorted-order segments). Every node's
    segment is padded to a multiple of the block size ``C`` so each
    C-row block belongs to exactly one node; total padded length is the
    static ``ceil(n/C)*C + N*C``. Returns (snode, valid, src_sorted,
    pstarts, pends, pcounts, nb) where ``src_sorted`` maps padded slots
    to sorted-row positions and ``valid`` masks the real rows.
    """
    N = counts.shape[0]
    ends = jnp.cumsum(counts)
    starts = ends - counts
    pcounts = ((counts + C - 1) // C) * C
    pends = jnp.cumsum(pcounts)
    pstarts = pends - pcounts
    n_pad = (-(-n // C)) * C + N * C
    nb = n_pad // C
    block_first = jnp.arange(nb, dtype=jnp.int32) * C
    bnode = jnp.clip(jnp.searchsorted(pends, block_first, side="right"),
                     0, N - 1).astype(jnp.int32)
    snode = jnp.repeat(bnode, C, total_repeat_length=n_pad)
    slot = jnp.arange(n_pad, dtype=jnp.int32)
    within = slot - pstarts[snode]
    valid = (within >= 0) & (within < counts[snode])
    src_sorted = jnp.clip(starts[snode] + within, 0, max(n - 1, 0))
    return snode, valid, src_sorted, pstarts, pends, pcounts, nb


def _sorted_hist(Xp, gp, hp, layout, *, n_bins: int, C: int, acc_dtype,
                 engine: str = "einsum"):
    """[N, d, B] grad/hess histograms from the padded block layout.

    Per block: a [C, d*B] bin one-hot contracted with the [C, 2] (g, h)
    rows on the MXU; per-node totals come from a block-axis cumsum and
    one boundary diff per node — no scatter anywhere, and the work is
    proportional to padded rows, not nodes.

    ``engine="pallas"`` runs the fused VMEM kernel
    (``ops/sorted_hist_pallas.py``): the one-hot never reaches HBM and
    the block cumsum is accumulated in scratch during the same pass.
    ``"einsum"`` is the pure-XLA oracle (and the off-TPU default).
    """
    snode, valid, src_sorted, pstarts, pends, pcounts, nb = layout
    counts_pos = pcounts > 0
    n_pad, d = Xp.shape
    B = n_bins
    Xpb = Xp.reshape(nb, C, d)
    if engine == "pallas" and B > 256:
        engine = "einsum"  # kernel's bf16 code broadcast is exact to 256
    if engine == "pallas":
        from transmogrifai_tpu.ops.sorted_hist_pallas import (
            sorted_block_hist,
        )
        ghb_k = jnp.stack([gp, hp]).reshape(2, nb, C).transpose(1, 0, 2)
        part_k = sorted_block_hist(Xpb, ghb_k, n_bins=B
                                   ).reshape(nb, 2, d, B)
        bc = jnp.cumsum(part_k, axis=0)
    else:
        ghb = jnp.stack([gp, hp], axis=-1).reshape(nb, C, 2).astype(
            acc_dtype)
        esize = jnp.dtype(acc_dtype).itemsize  # bf16 on TPU, f32 off it
        rows_per_chunk = max(C, _SORT_OH_BUDGET // (esize * d * B))
        cb = max(1, rows_per_chunk // C)
        n_chunks = -(-nb // cb)
        if n_chunks * cb != nb:
            pad = n_chunks * cb - nb
            Xpb = jnp.concatenate(
                [Xpb, jnp.zeros((pad, C, d), Xpb.dtype)])
            ghb = jnp.concatenate(
                [ghb, jnp.zeros((pad, C, 2), ghb.dtype)])
        iota_b = jnp.arange(B, dtype=jnp.int32).astype(Xpb.dtype)

        def chunk_part(args):
            xc, gc = args
            oh = (xc[..., None] == iota_b).astype(acc_dtype)
            return jnp.einsum("bcs,bcdk->bsdk", gc, oh,
                              preferred_element_type=jnp.float32)

        part = jax.lax.map(chunk_part,
                           (Xpb.reshape(n_chunks, cb, C, d),
                            ghb.reshape(n_chunks, cb, C, 2)))
        part = part.reshape(n_chunks * cb, 2, d, B)[:nb]
        bc = jnp.cumsum(part, axis=0)
    firstb = (pstarts // C).astype(jnp.int32)
    lastb = jnp.clip(pends // C - 1, 0, nb - 1)
    upper = bc[lastb]
    lower = jnp.where((firstb > 0)[:, None, None, None],
                      bc[jnp.clip(firstb - 1, 0, nb - 1)], 0.0)
    hist = jnp.where(counts_pos[:, None, None, None], upper - lower, 0.0)
    return hist[:, 0], hist[:, 1]


def _sorted_partition(counts, layout, go_left, src_row, n: int):
    """Stable in-segment partition: the next level's ``order`` groups rows
    by ``2*node + go_right`` using cumsums and one unique-index scatter —
    the incremental analog of re-sorting by node each level.
    """
    snode, valid, _, pstarts, pends, pcounts, _ = layout
    n_pad = snode.shape[0]
    N = counts.shape[0]
    glv = (go_left & valid).astype(jnp.int32)
    grv = ((~go_left) & valid).astype(jnp.int32)
    cl = jnp.cumsum(glv)
    cr = jnp.cumsum(grv)
    pfirst = jnp.clip(pstarts - 1, 0, n_pad - 1)
    plast = jnp.clip(pends - 1, 0, n_pad - 1)
    base_l = jnp.where(pstarts > 0, cl[pfirst], 0)
    base_r = jnp.where(pstarts > 0, cr[pfirst], 0)
    nl = jnp.where(pcounts > 0, cl[plast] - base_l, 0)
    new_counts = jnp.stack([nl, counts - nl], axis=1).reshape(2 * N)
    new_ends = jnp.cumsum(new_counts)
    new_starts = new_ends - new_counts
    pl = cl - glv - base_l[snode]
    pr = cr - grv - base_r[snode]
    dest = jnp.where(go_left, new_starts[2 * snode] + pl,
                     new_starts[2 * snode + 1] + pr)
    # invalid slots get DISTINCT out-of-range sentinels (n + slot) so the
    # unique_indices promise stays true even for dropped updates
    dest = jnp.where(valid, dest, n + jnp.arange(n_pad, dtype=jnp.int32))
    new_order = jnp.zeros(n, jnp.int32).at[dest].set(
        src_row, mode="drop", unique_indices=True)
    return new_order, new_counts


def _segment_sums(vals_sorted, counts):
    """[N] per-segment sums of an [n] array laid out in segment order,
    via one cumsum + boundary diffs (no scatter)."""
    n = vals_sorted.shape[0]
    ends = jnp.cumsum(counts)
    starts = ends - counts
    c = jnp.cumsum(vals_sorted)
    upper = c[jnp.clip(ends - 1, 0, max(n - 1, 0))]
    lower = jnp.where(starts > 0, c[jnp.clip(starts - 1, 0, max(n - 1, 0))],
                      0.0)
    return jnp.where(counts > 0, upper - lower, 0.0)


def _grow_tree_sorted(Xb, grad, hess, feat_mask, *, max_depth: int,
                      n_bins: int, reg_lambda, gamma, min_child_weight,
                      block: int = _SORT_BLOCK,
                      sorted_engine: str = "einsum",
                      sorted_acc: str = "auto",
                      data_axis=None):
    """Sort-based level-wise histogram tree (single-shard hot path).

    Same contract as the scatter-path ``grow_tree`` body: returns
    (feats, bins, leaf_values, feat_gain, row_pred). Maintains ``order``
    (row ids
    grouped by node) and per-node ``counts`` across levels so each level
    runs: one int8 row gather into the padded block layout, one MXU
    one-hot contraction for ALL (node, feature, bin) histograms, a
    cumsum boundary diff, and a cumsum-based stable partition. No
    scatter-adds and no node-count-dependent chunking (see
    scripts/tpu_calibrate3.py for the on-chip shootout this encodes).
    """
    n, d = Xb.shape
    B = n_bins
    # bin codes are < B; pack to the narrowest gatherable int so the
    # per-level row gather moves 4x fewer bytes
    Xb_n = Xb.astype(jnp.int8) if B <= 127 else Xb.astype(jnp.int32)
    if sorted_acc == "f32":
        acc_dtype = jnp.float32
    elif sorted_acc == "bf16":
        acc_dtype = jnp.bfloat16
    else:  # auto: the measured on-chip default
        acc_dtype = jnp.bfloat16 if jax.default_backend() == "tpu" \
            else jnp.float32
    engine = sorted_engine
    if engine == "pallas" and acc_dtype == jnp.float32 \
            and jax.default_backend() == "tpu":
        # the fused kernel's one-hot broadcast is bf16-only; a forced-f32
        # accumulation must really accumulate in f32, so take the XLA path
        engine = "einsum"
    split_kw = dict(n_bins=B, reg_lambda=reg_lambda, gamma=gamma,
                    min_child_weight=min_child_weight)
    order = jnp.arange(n, dtype=jnp.int32)
    counts = jnp.full((1,), n, jnp.int32)
    feats_out, bins_out = [], []
    feat_gain = jnp.zeros(d, jnp.float32)
    for level in range(max_depth):
        N = 2 ** level
        C = min(block, _pow2_at_most(max(n // (2 * N), 8)))
        layout = _sorted_layout(counts, n, C)
        snode, valid, src_sorted, *_ = layout
        src_row = order[src_sorted]
        Xp = Xb_n[src_row]
        vf = valid.astype(grad.dtype)
        gp = grad[src_row] * vf
        hp = hess[src_row] * vf
        hist_g, hist_h = _sorted_hist(Xp, gp, hp, layout, n_bins=B, C=C,
                                      acc_dtype=acc_dtype, engine=engine)
        if data_axis is not None:
            # distributed fit (explicit shard_map): per-shard local
            # histograms all-reduce once per level — the Rabit/MLlib
            # executor-aggregation analog on ICI — after which every
            # shard takes identical split decisions and routes its own
            # rows (order/counts stay shard-local)
            hist_g = jax.lax.psum(hist_g, data_axis)
            hist_h = jax.lax.psum(hist_h, data_axis)
        feat, bin_, gain = _best_splits(hist_g, hist_h, feat_mask,
                                        **split_kw)
        feats_out.append(feat)
        bins_out.append(bin_)
        feat_gain = feat_gain.at[jnp.clip(feat, 0)].add(gain)
        fp = feat[snode]
        bp = bin_[snode]
        xp = jnp.take_along_axis(
            Xp, jnp.clip(fp, 0)[:, None].astype(jnp.int32),
            axis=1)[:, 0].astype(jnp.int32)
        go_left = jnp.where(fp < 0, True, xp <= bp)
        order, counts = _sorted_partition(counts, layout, go_left,
                                          src_row, n)
    leaf_g = _segment_sums(grad[order], counts)
    leaf_h = _segment_sums(hess[order], counts)
    if data_axis is not None:
        leaf_g = jax.lax.psum(leaf_g, data_axis)
        leaf_h = jax.lax.psum(leaf_h, data_axis)
    leaf_values = -leaf_g / (leaf_h + reg_lambda)
    # per-row predictions from the maintained segment order: leaf value of
    # each sorted row, scattered back to original row ids (unique indices)
    ends = jnp.cumsum(counts)
    snode_final = jnp.searchsorted(ends, jnp.arange(n), side="right"
                                   ).astype(jnp.int32)
    row_pred = jnp.zeros(n, leaf_values.dtype).at[order].set(
        leaf_values[snode_final], unique_indices=True)
    return tuple(feats_out), tuple(bins_out), leaf_values, feat_gain, \
        row_pred


def _best_splits(hist_g, hist_h, feat_mask, *, n_bins, reg_lambda, gamma,
                 min_child_weight):
    """XGBoost gain formula over [nodes, d, B] histograms via bin-axis
    cumsums. Returns per-node (feat, bin): feat -1 / bin B on no-split
    (Xb <= B is always true -> such nodes route every row left)."""
    n_nodes, d, B = hist_g.shape
    GL = jnp.cumsum(hist_g, axis=2)
    HL = jnp.cumsum(hist_h, axis=2)
    G = GL[:, :, -1:]
    H = HL[:, :, -1:]
    GR = G - GL
    HR = H - HL
    gain = 0.5 * (GL ** 2 / (HL + reg_lambda)
                  + GR ** 2 / (HR + reg_lambda)
                  - G ** 2 / (H + reg_lambda)) - gamma
    bad = (HL < min_child_weight) | (HR < min_child_weight)
    gain = jnp.where(bad, -jnp.inf, gain)
    gain = jnp.where(feat_mask[None, :, None] > 0, gain, -jnp.inf)
    # last bin can't split (right side empty by construction)
    gain = gain.at[:, :, B - 1].set(-jnp.inf)
    flat_gain = gain.reshape(n_nodes, d * B)
    best = jnp.argmax(flat_gain, axis=1)
    best_gain = jnp.take_along_axis(flat_gain, best[:, None], axis=1)[:, 0]
    feat = (best // B).astype(jnp.int32)
    bin_ = (best % B).astype(jnp.int32)
    no_split = ~(best_gain > 0.0)
    feat = jnp.where(no_split, -1, feat)
    bin_ = jnp.where(no_split, B, bin_)
    gain_out = jnp.where(no_split, 0.0, best_gain)
    return feat, bin_, gain_out


@functools.partial(jax.jit, static_argnames=("max_depth", "n_bins",
                                             "max_hist_nodes",
                                             "hist", "sorted_engine",
                                             "sorted_acc", "data_axis"))
def grow_tree(Xb, grad, hess, feat_mask, *, max_depth: int, n_bins: int,
              reg_lambda, gamma, min_child_weight,
              max_hist_nodes: int = _MAX_HIST_NODES, hist: str = "scatter",
              sorted_engine: str = "einsum", sorted_acc: str = "auto",
              data_axis=None):
    """Level-wise histogram tree. Returns (feats, bins, leaf_values,
    feat_gain, row_pred): feats/bins are tuples of per-level [2^level]
    arrays, leaf_values is [2^max_depth], feat_gain is the [d] per-feature
    split-gain total, and row_pred is each training row's leaf value (so
    boosting loops skip the re-descent). grad/hess already carry row
    weights.

    ``hist`` selects the histogram engine:

    - ``"scatter"`` (default): flat-index scatter-adds — the GSPMD-safe
      path (per-shard scatters + XLA-inserted psum under a mesh) and the
      cheapest at small n.
    - ``"sorted"``: the sort-based MXU path (``_grow_tree_sorted``) —
      ~7x faster per level on the real chip at 1M rows and node-count
      independent; meant for large single-shard fits (the bench path).

    Memory discipline for deep trees (reference RF default depth=12,
    README.md:60-80) on the scatter path: while a level's [nodes, d, B]
    histograms fit ``max_hist_nodes`` they are materialized once and the
    level uses the classic sibling-subtraction trick — only LEFT children
    are scattered, right = parent - left, halving scatter work; deeper
    levels switch to a ``lax.map`` over node chunks that keeps only
    per-node split decisions, so peak HBM stays O(max_hist_nodes * d * B)
    at any depth. The sorted path needs neither trick.
    """
    if hist == "sorted":
        return _grow_tree_sorted(
            Xb, grad, hess, feat_mask, max_depth=max_depth, n_bins=n_bins,
            reg_lambda=reg_lambda, gamma=gamma,
            min_child_weight=min_child_weight, sorted_engine=sorted_engine,
            sorted_acc=sorted_acc, data_axis=data_axis)
    if hist != "scatter":
        raise ValueError(f"hist={hist!r}: expected 'scatter' or 'sorted'")
    if data_axis is not None:
        # the scatter path has no in-body all-reduce: running it under a
        # shard_map with data_axis would silently grow divergent
        # per-shard trees (use GSPMD sharding for scatter instead)
        raise ValueError("data_axis requires hist='sorted'")
    from transmogrifai_tpu.ops.histograms import node_bin_histogram_xla
    n, d = Xb.shape
    B = n_bins
    # node counts are powers of two; round the budget down to one so the
    # chunked levels tile exactly (a non-power-of-two budget would otherwise
    # fail deep inside lax.map with a reshape error)
    max_hist_nodes = 1 << (max(int(max_hist_nodes), 1).bit_length() - 1)
    split_kw = dict(n_bins=B, reg_lambda=reg_lambda, gamma=gamma,
                    min_child_weight=min_child_weight)

    def hist_of(node_ids, g, h, n_nodes):
        return node_bin_histogram_xla(Xb, node_ids, g, h,
                                      n_nodes=n_nodes, n_bins=B)

    node = jnp.zeros(n, dtype=jnp.int32)
    rows = jnp.arange(n)
    feats_out, bins_out = [], []
    feat_gain = jnp.zeros(d, jnp.float32)  # per-feature split-gain totals
    prev_hist = None  # previous level's full (g, h) histograms, if kept
    for level in range(max_depth):
        n_nodes = 2 ** level
        if n_nodes <= max_hist_nodes:
            if prev_hist is None:
                hist_g, hist_h = hist_of(node, grad, hess, n_nodes)
            else:
                # sibling subtraction: scatter left children (even node ids)
                # under their PARENT index; right = parent - left
                is_left = (node % 2 == 0).astype(grad.dtype)
                half = n_nodes // 2
                lg, lh = hist_of(node // 2, grad * is_left, hess * is_left,
                                 half)
                pg, ph = prev_hist
                hist_g = jnp.stack([lg, pg - lg], axis=1).reshape(
                    n_nodes, d, B)
                hist_h = jnp.stack([lh, ph - lh], axis=1).reshape(
                    n_nodes, d, B)
            prev_hist = (hist_g, hist_h)
            feat, bin_, gain = _best_splits(hist_g, hist_h, feat_mask,
                                            **split_kw)
        else:
            # node-chunked: histogram + split per chunk, O(chunk*d*B) memory
            prev_hist = None
            n_chunks = n_nodes // max_hist_nodes

            def chunk_splits(c):
                base = c * max_hist_nodes
                in_chunk = ((node >= base) & (node < base + max_hist_nodes))
                mask = in_chunk.astype(grad.dtype)
                local = jnp.where(in_chunk, node - base, 0).astype(jnp.int32)
                hg, hh = hist_of(local, grad * mask, hess * mask,
                                 max_hist_nodes)
                return _best_splits(hg, hh, feat_mask, **split_kw)

            feat_c, bin_c, gain_c = jax.lax.map(chunk_splits,
                                                jnp.arange(n_chunks))
            feat = feat_c.reshape(n_nodes)
            bin_ = bin_c.reshape(n_nodes)
            gain = gain_c.reshape(n_nodes)
        feats_out.append(feat)
        bins_out.append(bin_)
        # gain-based importances (reference ModelInsights extracts real
        # gain importances from the boosters): accumulate each realized
        # split's gain under its feature; clip(-1 -> 0) is safe because
        # no-split nodes carry gain 0
        feat_gain = feat_gain.at[jnp.clip(feat, 0)].add(gain)
        f_row = feat[node]
        b_row = bin_[node]
        x_row = Xb[rows, jnp.clip(f_row, 0)]
        go_left = jnp.where(f_row < 0, True, x_row <= b_row)
        node = node * 2 + jnp.where(go_left, 0, 1).astype(jnp.int32)
    # leaf values from accumulated grad/hess at the final nodes
    n_leaves = 2 ** max_depth
    leaf_g = jnp.zeros(n_leaves, jnp.float32).at[node].add(grad)
    leaf_h = jnp.zeros(n_leaves, jnp.float32).at[node].add(hess)
    leaf_values = -leaf_g / (leaf_h + reg_lambda)
    # training-row predictions come free from the final node assignment —
    # the boosting loop must not pay a full re-descent (d more gathers)
    row_pred = leaf_values[node]
    return tuple(feats_out), tuple(bins_out), leaf_values, feat_gain, \
        row_pred


def predict_tree(Xb, feats, bins, leaf_values):
    n = Xb.shape[0]
    rows = jnp.arange(n)
    node = jnp.zeros(n, dtype=jnp.int32)
    for level in range(len(feats)):
        f = feats[level][node]
        b = bins[level][node]
        x = Xb[rows, jnp.clip(f, 0)]
        go_left = jnp.where(f < 0, True, x <= b)
        node = node * 2 + jnp.where(go_left, 0, 1).astype(jnp.int32)
    return leaf_values[node]


# ---------------------------------------------------------------------------
# boosting / forest training loops
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=(
    "n_rounds", "max_depth", "n_bins", "n_out", "loss", "seed",
    "bootstrap", "subsample", "colsample", "max_hist_nodes",
    "hist", "sorted_engine", "sorted_acc", "data_axis"))
def train_ensemble(Xb, y, w, *, n_rounds: int, max_depth: int, n_bins: int,
                   n_out: int, loss: str, learning_rate, reg_lambda, gamma,
                   min_child_weight, subsample, colsample, base_score,
                   bootstrap: bool, seed: int,
                   max_hist_nodes: int = _MAX_HIST_NODES,
                   hist: str = "scatter", sorted_engine: str = "einsum",
                   sorted_acc: str = "auto", data_axis=None):
    """Train a whole ensemble in one scanned program.

    loss: 'logistic' (n_out=1), 'softmax' (n_out=K one-vs-all), 'squared'.
    bootstrap=True grows independent trees on Poisson(1) row weights from
    the base margin (random forest); otherwise rounds are boosted.
    """
    n, d = Xb.shape
    key0 = jax.random.PRNGKey(seed)

    def margins_zero():
        return jnp.broadcast_to(base_score, (n, n_out)).astype(jnp.float32)

    def grads(margin):
        if loss == "logistic":
            p = jax.nn.sigmoid(margin[:, 0])
            return (p - y)[:, None], (p * (1 - p))[:, None]
        if loss == "softmax":
            t = jax.nn.one_hot(y.astype(jnp.int32), n_out)
            p = jax.nn.sigmoid(margin)  # one-vs-all logistic per class
            return p - t, p * (1 - p)
        if loss == "squared_onehot":
            # multiclass forest: per-class regression trees on the one-hot
            # target, all classes vmapped in THIS one program (leaf value =
            # weighted class frequency, the gini-style probability estimate)
            t = jax.nn.one_hot(y.astype(jnp.int32), n_out)
            return margin - t, jnp.ones_like(margin)
        return margin - y[:, None], jnp.ones_like(margin)

    def one_round(carry, key):
        margin = carry
        g, h = grads(margin)
        k_rows, k_cols = jax.random.split(key)
        if data_axis is not None:
            # distributed: row-sampling draws must be INDEPENDENT per
            # shard (fold in the shard index) while the feature mask
            # below must stay IDENTICAL across shards (k_cols unfolded)
            k_rows = jax.random.fold_in(k_rows,
                                        jax.lax.axis_index(data_axis))
        if bootstrap:
            rw = jax.random.poisson(k_rows, subsample, (n,)).astype(jnp.float32)
        elif subsample < 1.0:
            rw = (jax.random.uniform(k_rows, (n,)) < subsample
                  ).astype(jnp.float32)
        else:
            rw = jnp.ones(n, jnp.float32)
        rw = rw * w
        fmask = (jax.random.uniform(k_cols, (d,)) < colsample
                 ).astype(jnp.float32)
        fmask = jnp.where(jnp.sum(fmask) < 1.0, jnp.ones(d, jnp.float32),
                          fmask)

        def grow_one(gk, hk):
            return grow_tree(Xb, gk * rw, hk * rw, fmask,
                             max_depth=max_depth, n_bins=n_bins,
                             reg_lambda=reg_lambda, gamma=gamma,
                             min_child_weight=min_child_weight,
                             max_hist_nodes=max_hist_nodes, hist=hist,
                             sorted_engine=sorted_engine,
                             sorted_acc=sorted_acc,
                             data_axis=data_axis)

        feats, bins, leaves, gains, preds = jax.vmap(
            grow_one, in_axes=(1, 1))(g, h)
        # feats/bins: tuples of [n_out, 2^level]; leaves [n_out, 2^depth];
        # preds [n_out, n] come from the grower's final node assignment
        # (no re-descent)
        if bootstrap:
            new_margin = margin  # forest trees are independent
        else:
            new_margin = margin + learning_rate * preds.T
        return new_margin, ((feats, bins, leaves), jnp.sum(gains, axis=0))

    keys = jax.random.split(key0, n_rounds)
    _, (trees, gains) = jax.lax.scan(one_round, margins_zero(), keys)
    # trees: pytree with leading [n_rounds] axis; gains: [n_rounds, d]
    return trees, jnp.sum(gains, axis=0)


def train_ensemble_sharded(ctx, Xb, y, w, **kw):
    """Distributed ensemble fit: the SORTED engine under an explicit
    ``shard_map`` over the mesh's data axis.

    Each shard keeps its own rows' sort bookkeeping (order/counts) and
    contributes per-level local histograms; one [N, d, B] psum per level
    (plus one for the leaf sums) replicates the split decisions — the
    XLA-collective analog of XGBoost's Rabit all-reduce / Spark MLlib's
    executor histogram aggregation (SURVEY §2.7 P5), now on the engine
    that is 5-7x faster per level than the scatter path. Row sampling
    folds the shard index into the per-round key (independent draws);
    the colsample mask deliberately does not (must match across shards).

    ``Xb``/``y``/``w`` must be row-sharded over ``ctx.mesh``'s data axis
    (rows padded to the shard multiple with weight 0 — what
    ``parallel.mesh.shard_training_rows`` produces). Returns the same
    (trees, gains) as ``train_ensemble``, replicated.
    """
    from jax.sharding import PartitionSpec as P
    from transmogrifai_tpu.parallel.mesh import DATA_AXIS, shard_map_compat

    def shard_fn(Xb_s, y_s, w_s):
        return train_ensemble(Xb_s, y_s, w_s, hist="sorted",
                              data_axis=DATA_AXIS, **kw)

    fn = shard_map_compat(
        shard_fn, mesh=ctx.mesh,
        in_specs=(P(DATA_AXIS, None), P(DATA_AXIS), P(DATA_AXIS)),
        out_specs=P(), check_vma=False)
    return fn(Xb, y, w)


@functools.partial(jax.jit, static_argnames=(
    "n_rounds", "max_depth", "n_bins", "loss", "subsample",
    "colsample", "bootstrap", "seed", "hist", "sorted_engine", "sorted_acc",
    "forest_margin"))
def train_score_stacked(Xb, y, w, Xva, base, lr, lam, gam, mcw, *,
                        n_rounds: int, max_depth: int, n_bins: int,
                        loss: str, subsample, colsample,
                        bootstrap: bool, seed: int, hist: str,
                        sorted_engine: str, sorted_acc: str,
                        forest_margin: bool):
    """ONE compiled program for a whole (family, depth-group) of the CV
    sweep: train all ``k`` folds x ``L`` same-shape grid lanes and score
    their validation folds, returning ``[k, L, n_va]`` scores.

    ``Xb/Xva``: ``[k, n, d]`` stacked int bin codes (one fold gather of
    the dataset-level ``fold_sweep_plan`` codes — no re-binning);
    ``y/w``: ``[k, n]``; ``base``: ``[k]`` per-fold base scores
    (host-computed with the loop path's exact f32/f64 arithmetic —
    ``tree_stack_fold_bases`` — so stacked-vs-loop parity stays bitwise);
    ``lr/lam/gam/mcw``: ``[L]`` per-lane hyperparameter scalars riding as
    batched operands. The fold axis is the outer ``vmap``, lanes the
    inner one, so the existing ``lax.scan``-over-rounds grower batches:
    the sorted engine's one-hot contraction gains MXU batch dims
    (node-count-independent, the extra axis feeds the systolic array),
    and the scatter engine's histograms fold every batch axis into the
    node axis via the ``custom_vmap`` rule in ``ops/histograms.py`` —
    one flat scatter per level for the whole (fold x lane x class)
    batch. ``forest_margin`` re-centers forest-classifier probabilities
    at 0, matching ``grid_predict_scores``.
    """

    def fold_fn(Xb_k, y_k, w_k, Xva_k, base_k):
        def lane_fn(lr_i, lam_i, gam_i, mcw_i):
            trees, _gains = train_ensemble(
                Xb_k, y_k, w_k, n_rounds=n_rounds, max_depth=max_depth,
                n_bins=n_bins, n_out=1, loss=loss, learning_rate=lr_i,
                reg_lambda=lam_i, gamma=gam_i, min_child_weight=mcw_i,
                subsample=subsample, colsample=colsample,
                base_score=base_k, bootstrap=bootstrap, seed=seed,
                hist=hist, sorted_engine=sorted_engine,
                sorted_acc=sorted_acc)
            out = predict_ensemble(Xva_k, trees, n_out=1,
                                   learning_rate=lr_i, base_score=base_k,
                                   bootstrap=bootstrap)
            s = out[:, 0]
            if forest_margin:
                s = jnp.clip(s, 0.0, 1.0) - 0.5  # margin at 0
            return s

        return jax.vmap(lane_fn)(lr, lam, gam, mcw)

    return jax.vmap(fold_fn)(Xb, y, w, Xva, base)


def predict_ensemble(Xb, trees, *, n_out: int, learning_rate, base_score,
                     bootstrap: bool):
    feats, bins, leaves = trees
    n_rounds = leaves.shape[0]

    def one_round(r):
        f = tuple(x[r] for x in feats)
        b = tuple(x[r] for x in bins)
        l = leaves[r]
        return jax.vmap(lambda ff, bb, ll: predict_tree(Xb, ff, bb, ll))(
            f, b, l)  # [n_out, n]

    preds = jax.vmap(one_round)(jnp.arange(n_rounds))  # [R, n_out, n]
    if bootstrap:
        return jnp.mean(preds, axis=0).T  # [n, n_out]
    return base_score + learning_rate * jnp.sum(preds, axis=0).T


# ---------------------------------------------------------------------------
# fitted model
# ---------------------------------------------------------------------------

class TreeEnsembleModel(PredictionModel):
    """Fitted ensemble. kind: 'gbt_classifier' | 'gbt_regressor' |
    'rf_classifier' | 'rf_regressor'."""

    def __init__(self, kind: str = "gbt_classifier", n_out: int = 1,
                 learning_rate: float = 0.3, base_score: float = 0.0,
                 max_depth: int = 6, uid: Optional[str] = None):
        self.kind = kind
        self.n_out = n_out
        self.learning_rate = learning_rate
        self.base_score = base_score
        self.max_depth = max_depth
        self.bin_edges: Optional[np.ndarray] = None
        self.trees = None  # (feats tuple, bins tuple, leaves) stacked [R,...]
        self.feature_gains = None  # [d] accumulated split gains (importance)
        super().__init__(uid=uid)

    @property
    def is_forest(self) -> bool:
        return self.kind.startswith("rf")

    @property
    def is_classifier(self) -> bool:
        return self.kind.endswith("classifier")

    def device_params(self):
        return (jnp.asarray(self.bin_edges), self.trees)

    def quantize_device_params(self, precision):
        from transmogrifai_tpu.utils.precision import ExactTensor, fits_int16
        edges, (feats, bins, leaves) = self.device_params()
        if precision == "int8" and all(fits_int16(a)
                                       for a in (*feats, *bins)):
            # node traversal compares binned int data: int16 vs int32
            # promotes exactly, so the threshold path is bitwise-safe
            feats = tuple(jnp.asarray(a, jnp.int16) for a in feats)
            bins = tuple(jnp.asarray(a, jnp.int16) for a in bins)
        # bin edges stay f32 master values at every rung (ExactTensor
        # pins them through the builder's generic float cast); leaf
        # values take the rung's activation dtype like any float param
        return (ExactTensor(edges), (feats, bins, leaves))

    def device_apply(self, params, col: fr.VectorColumn) -> fr.PredictionColumn:
        edges, trees = params
        Xb = bin_data(col.values, edges)
        out = predict_ensemble(
            Xb, trees, n_out=self.n_out,
            learning_rate=self.learning_rate, base_score=self.base_score,
            bootstrap=self.is_forest)  # [n, n_out]
        n = out.shape[0]
        if not self.is_classifier:
            empty = jnp.zeros((n, 0), jnp.float32)
            return fr.PredictionColumn(out[:, 0], empty, empty)
        if self.is_forest:
            # leaves hold class probabilities directly
            if self.n_out == 1:
                p1 = jnp.clip(out[:, 0], 0.0, 1.0)
                prob = jnp.stack([1 - p1, p1], axis=1)
            else:
                s = jnp.clip(out, 0.0, 1.0)
                prob = s / jnp.maximum(jnp.sum(s, axis=1, keepdims=True), 1e-12)
            raw = prob
        else:
            if self.n_out == 1:
                p1 = jax.nn.sigmoid(out[:, 0])
                prob = jnp.stack([1 - p1, p1], axis=1)
                raw = jnp.stack([-out[:, 0], out[:, 0]], axis=1)
            else:
                prob = jax.nn.softmax(out, axis=1)
                raw = out
        pred = jnp.argmax(prob, axis=1).astype(jnp.float32)
        return fr.PredictionColumn(pred, raw, prob)

    # -- persistence ---------------------------------------------------------
    def fitted_state(self):
        feats, bins, leaves = self.trees
        state = {"bin_edges": np.asarray(self.bin_edges),
                 "leaves": np.asarray(leaves)}
        if self.feature_gains is not None:
            state["feature_gains"] = np.asarray(self.feature_gains)
        for l, (f, b) in enumerate(zip(feats, bins)):
            state[f"feat_l{l}"] = np.asarray(f)
            state[f"bin_l{l}"] = np.asarray(b)
        return state

    def set_fitted_state(self, state):
        self.bin_edges = np.asarray(state["bin_edges"])
        leaves = jnp.asarray(state["leaves"])
        if "feature_gains" in state:
            self.feature_gains = np.asarray(state["feature_gains"])
        feats, bins = [], []
        for l in range(self.max_depth):
            feats.append(jnp.asarray(state[f"feat_l{l}"]))
            bins.append(jnp.asarray(state[f"bin_l{l}"]))
        self.trees = (tuple(feats), tuple(bins), leaves)

    def config(self):
        base = self.base_score
        if np.ndim(base):  # per-class vector (imported multiclass GBMs)
            base = [float(b) for b in np.asarray(base)]
        return {"kind": self.kind, "n_out": self.n_out,
                "learning_rate": self.learning_rate,
                "base_score": base, "max_depth": self.max_depth}

    @classmethod
    def from_config(cls, config, uid=None):
        config = dict(config)
        if isinstance(config.get("base_score"), (list, tuple)):
            config["base_score"] = np.asarray(config["base_score"],
                                              np.float32)
        return cls(uid=uid, **config)

    def feature_contributions(self) -> np.ndarray:
        """Gain-based importance shares (reference ModelInsights extracts
        real gain importances per model type, ``ModelInsights.scala:64-858``;
        XGBoost 'total_gain' semantics): each feature's share of the total
        split gain accumulated during growth. Falls back to depth-weighted
        split frequency for models restored from pre-gain manifests."""
        if self.feature_gains is not None:
            imp = np.maximum(np.asarray(self.feature_gains, np.float64), 0.0)
            total = imp.sum()
            return imp / total if total > 0 else imp
        feats, _, _ = self.trees
        d = int(self.bin_edges.shape[0])
        imp = np.zeros(d)
        for level, f in enumerate(feats):
            arr = np.asarray(f).reshape(-1)
            wgt = 1.0 / (2 ** level)
            for v in arr[arr >= 0]:
                imp[int(v)] += wgt
        total = imp.sum()
        return imp / total if total > 0 else imp


# ---------------------------------------------------------------------------
# estimators
# ---------------------------------------------------------------------------

class _TreePredictor(Predictor):
    kind = "gbt_classifier"
    loss = "logistic"
    bootstrap = False

    default_params = {
        "num_rounds": 50,        # trees (forest) / boosting rounds (gbt)
        "max_depth": 6,
        "max_bins": 64,
        "learning_rate": 0.3,    # eta / stepSize
        "reg_lambda": 1.0,
        "gamma": 0.0,
        "min_child_weight": 1.0,
        "subsample": 1.0,
        "colsample": 1.0,
        "seed": 42,
    }

    # forest synonyms accepted in grids
    _ALIASES = {"num_trees": "num_rounds", "eta": "learning_rate",
                "step_size": "learning_rate"}

    def __init__(self, uid=None, **params):
        params = {self._ALIASES.get(k, k): v for k, v in params.items()}
        super().__init__(uid=uid, **params)

    def _loss_and_nout(self, y, _stats=None) -> tuple[str, int, float]:
        """(loss, n_out, base score). ``_stats`` is the selector's
        once-per-sweep host pull of ``(max(y), mean(y),
        clip(mean(y), 1e-6, 1-1e-6))`` — each value produced by the SAME
        device expression this method would run, so the threaded route is
        bitwise-identical to the per-family blocking pull it elides on
        the one-sync dispatch path."""
        if self.loss == "squared":
            mean = _stats[1] if _stats is not None else jnp.mean(y)
            return "squared", 1, float(mean)
        y_max = (_stats[0] if _stats is not None
                 else np.asarray(jnp.max(y)))
        n_classes = int(y_max) + 1
        if n_classes <= 2:
            clipped = (_stats[2] if _stats is not None
                       else jnp.clip(jnp.mean(y), 1e-6, 1 - 1e-6))
            p = float(clipped)
            base = 0.0 if self.bootstrap else float(np.log(p / (1 - p)))
            return "logistic", 1, base
        return "softmax", n_classes, 0.0

    def _stacked_base_mode(self, loss: str) -> str:
        """How the fold x grid-stacked program derives each fold's base
        score IN-PROGRAM — must mirror ``_loss_and_nout``'s base exactly
        (the stacked-vs-loop parity contract), so overrides pair with it:
        ``"mean"`` = fold label mean (squared losses, forests included —
        forests trained on a mean base fit residuals whose base is never
        re-added at predict, the established semantics), ``"logodds"`` =
        log-odds of the fold's positive rate, ``"zero"`` = 0."""
        if loss == "squared":
            return "mean"
        return "zero" if self.bootstrap else "logodds"

    def _edges_of(self, X, max_bins: int):
        """Quantile edges; device path for device-resident X (no host pull),
        host percentile for plain numpy input."""
        if isinstance(X, jax.Array):
            return quantile_bin_edges_device(X, max_bins=max_bins)
        return jnp.asarray(quantile_bin_edges(np.asarray(X), max_bins))

    def fit_arrays(self, X, y, w, params, _binned=None, _lnb=None):
        params = {self._ALIASES.get(k, k): v for k, v in params.items()}
        p = {**self.default_params, **params}
        # (loss, n_out, base) involves blocking device->host scalar pulls
        # (max/mean of y); grid sweeps compute it once and thread it here
        loss, n_out, base = _lnb if _lnb is not None \
            else self._loss_and_nout(y)
        if _binned is not None and int(p["max_bins"]) == _binned[2]:
            edges, Xb = _binned[0], _binned[1]
        else:
            edges = self._edges_of(X, int(p["max_bins"]))
            Xb = bin_data(X, edges)
        subsample = p["subsample"] if not self.bootstrap else 1.0
        from transmogrifai_tpu.utils import flops
        n, d = int(Xb.shape[0]), int(Xb.shape[1])
        depth, rounds, B = int(p["max_depth"]), int(p["num_rounds"]), \
            int(p["max_bins"])
        hist_mode = _hist_mode_for(Xb)
        if hist_mode.startswith("sorted"):
            # per level: padded-row one-hot contraction 4*n*d*B MXU MACs
            # (g+h stats) + layout/partition cumsums ~10n + split eval
            per_tree = sum(4.0 * n * d * B + 10.0 * n
                           + 12.0 * (2 ** lv) * d * B
                           for lv in range(depth))
        else:
            # per level: flat-index + 2 scatter adds ~5nd update ops,
            # routing ~4n, split eval ~12*nodes*d*B; device update-ops,
            # not MXU FLOPs — scatter histogram work is bandwidth-bound
            # (see utils/flops.py docstring)
            per_tree = sum(5.0 * n * d + 4.0 * n + 12.0 * (2 ** lv) * d * B
                           for lv in range(depth))
        flops.add("tree", rounds * n_out * per_tree)
        ens_kw = dict(
            n_rounds=int(p["num_rounds"]), max_depth=int(p["max_depth"]),
            n_bins=int(p["max_bins"]), n_out=n_out, loss=loss,
            learning_rate=jnp.float32(p["learning_rate"]),
            reg_lambda=jnp.float32(p["reg_lambda"]),
            gamma=jnp.float32(p["gamma"]),
            min_child_weight=jnp.float32(p["min_child_weight"]),
            subsample=float(subsample),
            colsample=float(p["colsample"]),
            base_score=jnp.float32(base),
            bootstrap=self.bootstrap, seed=int(p["seed"]),
            sorted_engine=_sorted_engine_default(),
            sorted_acc=_sorted_acc_default())
        if hist_mode == "sorted_sharded":
            from transmogrifai_tpu.parallel.mesh import current_mesh
            trees, gains = train_ensemble_sharded(current_mesh(), Xb, y, w,
                                                  **ens_kw)
        else:
            trees, gains = train_ensemble(
                Xb, y, w, max_hist_nodes=_MAX_HIST_NODES,
                hist=hist_mode, **ens_kw)
        model = TreeEnsembleModel(
            kind=self.kind, n_out=n_out,
            learning_rate=float(p["learning_rate"]), base_score=base,
            max_depth=int(p["max_depth"]))
        model.bin_edges = edges
        model.trees = jax.tree_util.tree_map(lambda a: a, trees)
        model.feature_gains = gains  # device view; host pull is lazy
        return model


    def fold_sweep_plan(self, X, grid):
        """Dataset-level binning context for the selector's per-fold sweep:
        ``{max_bins: (edges, codes [n, d], max_bins)}`` computed ONCE on the
        full prepared training matrix; each fold's codes are then a cheap
        row gather instead of a fresh device quantile sort + searchsorted
        per fold (the sweep's k-fold re-binning was pure waste — edges
        barely move between a fold's (1 - 1/k) subset and the full matrix).

        Documented ``bin_once`` approximation: fold edges come from the
        whole training matrix, the XGBoost global-sketch analog; metrics
        shift by sub-bin-width amounts. ``TRANSMOGRIFAI_TREE_BIN_ONCE=0``
        disables the plan and restores exact per-fold quantile edges.
        Returns None when disabled."""
        import os
        if os.environ.get("TRANSMOGRIFAI_TREE_BIN_ONCE", "1") == "0":
            return None
        merged = [{self._ALIASES.get(k, k): v for k, v in g.items()}
                  for g in grid]
        plan: dict[int, tuple] = {}
        for g in merged:
            mb = int({**self.default_params, **self.params, **g}["max_bins"])
            if mb not in plan:
                edges = self._edges_of(X, mb)
                plan[mb] = (edges, bin_data(X, edges), mb)
        return plan

    def grid_fit_arrays(self, X, y, w, grid, _fold_plan=None,
                        _fold_rows=None):
        """Sequential grid (tree programs differ per static depth/rounds),
        but quantile-bin ONCE per (fold, family): edges depend only on X and
        max_bins, so grid points sharing max_bins reuse one binned matrix
        instead of paying a device sort + searchsorted each. With a
        ``_fold_plan`` (the selector's per-dataset ``fold_sweep_plan``) the
        binning collapses further to one row gather of the dataset-level
        codes (``_fold_rows`` are this fold's training row ids)."""
        merged = [{self._ALIASES.get(k, k): v for k, v in g.items()}
                  for g in grid]
        binned: dict[int, tuple] = {}
        lnb = self._loss_and_nout(y)  # ONE device sync for the whole grid
        models = []
        for g in merged:
            mb = int({**self.default_params, **self.params, **g}["max_bins"])
            if mb not in binned:
                if _fold_plan is not None and _fold_rows is not None \
                        and mb in _fold_plan:
                    edges, codes_full, _ = _fold_plan[mb]
                    binned[mb] = (edges,
                                  jnp.take(codes_full, _fold_rows, axis=0),
                                  mb)
                else:
                    edges = self._edges_of(X, mb)
                    binned[mb] = (edges, bin_data(X, edges), mb)
            models.append(self.fit_arrays(X, y, w, {**self.params, **g},
                                          _binned=binned[mb], _lnb=lnb))
        return models

    def grid_predict_scores(self, models, X):
        """Batched scoring when every grid model shares tree shapes (same
        max_depth/n_out): stack tree params and vmap one predict program."""
        if not models or not all(isinstance(m, TreeEnsembleModel)
                                 for m in models):
            return None
        m0 = models[0]
        if any(m.max_depth != m0.max_depth or m.n_out != m0.n_out
               or m.trees[2].shape != m0.trees[2].shape for m in models):
            return None
        if m0.n_out != 1:
            return None
        edges0 = m0.bin_edges
        same_edges = all(np.array_equal(m.bin_edges, edges0) for m in models)
        stacked = jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs), *[m.trees for m in models])
        Xb = bin_data(X, jnp.asarray(edges0)) if same_edges else None

        def score_one(trees, lr, base):
            out = predict_ensemble(Xb, trees, n_out=1, learning_rate=lr,
                                   base_score=base, bootstrap=m0.is_forest)
            s = out[:, 0]
            if m0.is_forest and m0.is_classifier:
                s = jnp.clip(s, 0.0, 1.0) - 0.5  # margin at 0
            return s

        if Xb is None:
            return None
        lrs = jnp.asarray([m.learning_rate for m in models], jnp.float32)
        bases = jnp.asarray([m.base_score for m in models], jnp.float32)
        return jax.vmap(score_one)(stacked, lrs, bases)

    # -- fold x grid-stacked sweep (round 8) ---------------------------------
    def tree_stack_groups(self, grid):
        """Group the grid by compiled-program shape — the static arguments
        of ``train_ensemble``: ``(max_depth, num_rounds, max_bins,
        subsample, colsample, seed)``. Each group's lanes share one
        compiled stacked program; the per-lane scalars (learning_rate,
        reg_lambda, gamma, min_child_weight) ride as batched operands.
        Returns ``[{lanes, params, max_depth, num_rounds, max_bins,
        subsample, colsample, seed}]`` in first-seen order (deterministic,
        so checkpoint group indices are stable across runs)."""
        merged = [{**self.default_params, **self.params,
                   **{self._ALIASES.get(k, k): v for k, v in g.items()}}
                  for g in grid]
        groups: dict[tuple, dict] = {}
        for i, p in enumerate(merged):
            # forests ignore the subsample grid value (fit_arrays pins the
            # Poisson rate to 1.0), so it must not split their groups
            sub = 1.0 if self.bootstrap else float(p["subsample"])
            key = (int(p["max_depth"]), int(p["num_rounds"]),
                   int(p["max_bins"]), sub, float(p["colsample"]),
                   int(p["seed"]))
            g = groups.setdefault(key, {
                "lanes": [], "params": [], "max_depth": key[0],
                "num_rounds": key[1], "max_bins": key[2],
                "subsample": key[3], "colsample": key[4], "seed": key[5]})
            g["lanes"].append(i)
            g["params"].append(p)
        return list(groups.values())

    def tree_stack_scalar_lnb(self, y, _stats=None):
        """``(loss, n_out, base)`` when the family has a scalar stacked
        score (binary margin / regression prediction), else None —
        multiclass has no batched scalar and keeps the per-fold loop.
        One blocking device sync (max of y) per FAMILY, elided by the
        selector's once-per-sweep ``_stats`` hint on the one-sync
        dispatch path (signature-gated: a subclass overriding
        ``_loss_and_nout`` with the old arity keeps its own probe)."""
        import inspect
        if _stats is not None and "_stats" in \
                inspect.signature(self._loss_and_nout).parameters:
            lnb = self._loss_and_nout(y, _stats=_stats)
        else:
            lnb = self._loss_and_nout(y)
        return lnb if lnb[1] == 1 else None

    @staticmethod
    def _tree_stack_hist_mode(n_rows: int) -> str:
        """Histogram engine for the stacked program — ``scatter`` or
        ``sorted``, never ``sorted_sharded``: the vmapped (fold x lane)
        batch cannot ride the explicit per-family ``shard_map`` wrapper,
        so under an active mesh the GSPMD scatter path (per-shard
        scatters + XLA-inserted psum) is the safe engine. Same
        TRANSMOGRIFAI_TREE_HIST override and loud-downgrade discipline as
        ``_hist_mode_for``; ``n_rows`` is one fold's training rows."""
        import os
        import warnings
        forced = os.environ.get("TRANSMOGRIFAI_TREE_HIST")
        if forced and forced not in ("scatter", "sorted"):
            raise ValueError(
                f"TRANSMOGRIFAI_TREE_HIST={forced!r}: expected 'scatter' "
                "or 'sorted'")
        from transmogrifai_tpu.parallel.mesh import current_mesh
        meshed = current_mesh() is not None
        if forced == "scatter":
            return "scatter"
        if forced == "sorted":
            if not meshed:
                return "sorted"
            msg = ("TRANSMOGRIFAI_TREE_HIST=sorted downgraded to 'scatter' "
                   "for the fold x grid-stacked tree sweep: the stacked "
                   "batch runs under GSPMD, where the sorted engine's "
                   "global-index bookkeeping would generate heavy "
                   "cross-shard collectives")
            if os.environ.get("TRANSMOGRIFAI_TREE_HIST_STRICT") == "1":
                raise RuntimeError(msg)
            warnings.warn(msg, RuntimeWarning)
            return "scatter"
        if (not meshed and n_rows >= _SORT_MIN_ROWS
                and jax.default_backend() == "tpu"):
            return "sorted"
        return "scatter"

    def tree_stack_bytes(self, k: int, n_tr: int, n_va: int, d: int,
                         group: dict) -> tuple[float, float]:
        """``(shared_bytes, per_lane_bytes)`` HBM estimate for one stacked
        depth-group — the tree-specific extension of the selector's
        ``fold_stack_unit_width`` guard. Shared: the stacked int8/int32
        code gathers plus labels/weights. Per lane (times k folds): the
        boosting margins/grad/hess/row-weight residency, both levels'
        (g, h) node-stat histograms, the sorted engine's materialized
        one-hot chunk when that engine is selected, and the ``[k, L,
        n_va]`` score slab. The selector divides the budget by this to
        split a group into lane chunks instead of falling all the way
        back to the per-fold loop."""
        B = int(group["max_bins"])
        depth = int(group["max_depth"])
        csize = 1 if B <= 127 else 4
        shared = float(k) * (float(n_tr + n_va) * d * csize
                             + 8.0 * n_tr + 4.0 * n_va)
        nodes = min(2 ** max(depth - 1, 0), _MAX_HIST_NODES)
        hist_bytes = 16.0 * nodes * d * B  # (g, h) x (level, prev) f32
        if self._tree_stack_hist_mode(n_tr) == "sorted":
            hist_bytes += min(float(_SORT_OH_BUDGET), 4.0 * n_tr * d * B)
        per_lane = float(k) * (28.0 * n_tr + hist_bytes + 8.0 * n_va)
        return shared, per_lane

    def tree_stack_fold_bases(self, fold_means, loss: str) -> np.ndarray:
        """``[k]`` per-fold base scores from the folds' label means,
        replicating ``_loss_and_nout``'s exact f32-clip + f64-log
        arithmetic on HOST so stacked-vs-loop metric parity is bitwise
        (an in-program f32 log differs by ~1 ulp, enough to move binned-
        metric bucket boundaries at scale)."""
        mode = self._stacked_base_mode(loss)
        means = np.asarray(fold_means, np.float32)
        if mode == "zero":
            return np.zeros(means.shape[0], np.float32)
        if mode == "mean":
            return means
        out = []
        for m in means:
            p = float(np.clip(m, np.float32(1e-6), np.float32(1 - 1e-6)))
            out.append(np.log(p / (1.0 - p)))
        return np.asarray(out, np.float32)

    def tree_stack_scores(self, Xb, y, w, Xva, lane_params, lnb,
                          fold_means=None):
        """``[k, L, n_va]`` validation scores for one (family,
        depth-group): the selector fast path's fused train+score unit.
        ``Xb/Xva`` are the stacked fold gathers of the dataset-level bin
        codes, ``lane_params`` the merged param dicts of this chunk's
        lanes (same static shape — ``tree_stack_groups`` guarantees it),
        ``lnb`` the family-level ``tree_stack_scalar_lnb``, and
        ``fold_means`` the folds' label means (the selector pulls them
        once per sweep; computed here — one sync — when absent). Returns
        None when no scalar stacked score exists (multiclass)."""
        loss, n_out, _base = lnb
        if n_out != 1 or not lane_params:
            return None
        p0 = lane_params[0]
        k, n_tr, d = (int(Xb.shape[0]), int(Xb.shape[1]), int(Xb.shape[2]))
        L = len(lane_params)
        if fold_means is None and self._stacked_base_mode(loss) != "zero":
            # each fold's mean comes from the SAME unbatched program the
            # loop path runs (a batched row-mean may re-associate)
            fold_means = np.asarray(jnp.stack(
                [jnp.mean(y[f]) for f in range(k)]))
        bases = jnp.asarray(self.tree_stack_fold_bases(
            fold_means if fold_means is not None else np.zeros(k), loss))
        lrs = jnp.asarray([p["learning_rate"] for p in lane_params],
                          jnp.float32)
        lams = jnp.asarray([p["reg_lambda"] for p in lane_params],
                           jnp.float32)
        gams = jnp.asarray([p["gamma"] for p in lane_params], jnp.float32)
        mcws = jnp.asarray([p["min_child_weight"] for p in lane_params],
                           jnp.float32)
        depth, rounds, B = (int(p0["max_depth"]), int(p0["num_rounds"]),
                            int(p0["max_bins"]))
        hist_mode = self._tree_stack_hist_mode(n_tr)
        from transmogrifai_tpu.utils import flops
        if hist_mode == "sorted":
            per_tree = sum(4.0 * n_tr * d * B + 10.0 * n_tr
                           + 12.0 * (2 ** lv) * d * B
                           for lv in range(depth))
        else:
            per_tree = sum(5.0 * n_tr * d + 4.0 * n_tr
                           + 12.0 * (2 ** lv) * d * B
                           for lv in range(depth))
        flops.add("tree", k * L * rounds * per_tree)
        return train_score_stacked(
            Xb, y, w, Xva, bases, lrs, lams, gams, mcws,
            n_rounds=rounds, max_depth=depth, n_bins=B, loss=loss,
            subsample=1.0 if self.bootstrap else float(p0["subsample"]),
            colsample=float(p0["colsample"]), bootstrap=self.bootstrap,
            seed=int(p0["seed"]), hist=hist_mode,
            sorted_engine=_sorted_engine_default(),
            sorted_acc=_sorted_acc_default(),
            forest_margin=self.bootstrap and self.kind.endswith("classifier"))

    # -- winner refit (round 9) ----------------------------------------------
    def refit_winner(self, X, y, w, params, *, warm=None, lane=None,
                     hints=None):
        """Full-data winner refit reusing the sweep's dataset-level bin
        codes: ``hints["bin_plans"]`` carries ``fold_sweep_plan``'s
        ``{max_bins: (edges, codes, max_bins)}`` computed on this SAME
        full training matrix, so the refit's duplicate quantile sort +
        searchsorted pass is deleted outright — ``fit_arrays`` would
        recompute byte-identical edges and codes from the identical
        ``X``, making the reuse bitwise-exact, not approximate. Loss/
        n_out/base are recomputed exactly as the serial refit always did
        (an O(1) scalar pull). Trees have no parameter warm start —
        ensemble growth cannot resume from fold trees."""
        merged = {self._ALIASES.get(k, k): v for k, v in params.items()}
        mb = int({**self.default_params, **self.params, **merged}
                 ["max_bins"])
        binned = ((hints or {}).get("bin_plans") or {}).get(mb)
        model = self.fit_arrays(X, y, w, params, _binned=binned)
        return model, binned is not None


class OpGBTClassifier(_TreePredictor):
    """Gradient-boosted classification trees (Spark OpGBTClassifier parity;
    one-vs-all logistic boosting for multiclass)."""
    kind = "gbt_classifier"
    loss = "logistic"
    bootstrap = False


class OpGBTRegressor(_TreePredictor):
    kind = "gbt_regressor"
    loss = "squared"
    bootstrap = False


class OpXGBoostClassifier(OpGBTClassifier):
    """XGBoost-parity surface (eta, lambda, gamma, min_child_weight,
    subsample/colsample) on the native histogram booster."""


class OpXGBoostRegressor(OpGBTRegressor):
    pass


class _ForestMixin:
    bootstrap = True

    default_params = {**_TreePredictor.default_params,
                      "num_rounds": 50, "max_depth": 12, "learning_rate": 1.0,
                      "subsample": 1.0, "colsample": 0.7,
                      "reg_lambda": 1e-3}


class OpRandomForestClassifier(_ForestMixin, _TreePredictor):
    """Bootstrap-aggregated probability trees (Spark RF parity).

    Multiclass grows per-class regression trees on the one-hot target with
    the class axis vmapped inside ONE compiled ensemble program (not K
    sequential host-loop fits)."""
    kind = "rf_classifier"
    loss = "squared"      # CART variance-reduction on the 0/1 target

    def _loss_and_nout(self, y, _stats=None):
        y_max = (_stats[0] if _stats is not None
                 else np.asarray(jnp.max(y)))
        n_classes = int(y_max) + 1
        if n_classes <= 2:
            return "squared", 1, 0.0
        return "squared_onehot", n_classes, 0.0

    def _stacked_base_mode(self, loss: str) -> str:
        return "zero"  # class-probability trees grow from a zero margin


class OpRandomForestRegressor(_ForestMixin, _TreePredictor):
    kind = "rf_regressor"
    loss = "squared"


class OpDecisionTreeClassifier(OpRandomForestClassifier):
    """Single CART tree: forest of one, no bootstrap, all features."""
    default_params = {**OpRandomForestClassifier.default_params,
                      "num_rounds": 1, "colsample": 1.0}

    def fit_arrays(self, X, y, w, params, _binned=None, _lnb=None):
        params = {**params, "num_rounds": 1, "colsample": 1.0}
        self.bootstrap = False  # a single tree sees the full sample
        try:
            return super().fit_arrays(X, y, w, params, _binned=_binned,
                                      _lnb=_lnb)
        finally:
            self.bootstrap = True


class OpDecisionTreeRegressor(OpRandomForestRegressor):
    default_params = {**OpRandomForestRegressor.default_params,
                      "num_rounds": 1, "colsample": 1.0}

    def fit_arrays(self, X, y, w, params, _binned=None, _lnb=None):
        params = {**params, "num_rounds": 1, "colsample": 1.0}
        self.bootstrap = False
        try:
            return super().fit_arrays(X, y, w, params, _binned=_binned,
                                      _lnb=_lnb)
        finally:
            self.bootstrap = True


