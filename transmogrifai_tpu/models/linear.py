"""Linear model family: logistic regression, linear SVC, linear regression.

Parity: reference ``stages/impl/classification/{OpLogisticRegression,
OpLinearSVC}.scala`` and ``stages/impl/regression/OpLinearRegression.scala``
— same hyperparameter surface (regParam, elasticNetParam, maxIter, tol,
fitIntercept, standardization).

TPU-first: training is full-batch gradient descent (Adam) expressed as one
``lax.scan`` jitted program — dense X rides in HBM, per-step compute is a
pair of [n,d]x[d,C] matmuls on the MXU in f32. The hyperparameter grid
trains as a *stacked leading axis* under ``vmap`` (``grid_fit_arrays``):
all L1/L2 candidates descend simultaneously in one XLA program, which is
the TPU replacement for the reference's CV thread pool (SURVEY §2.7 P3).
Standardization is folded into the weights at the end so scoring needs no
scaler state.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax

from transmogrifai_tpu import frame as fr
from transmogrifai_tpu.models.base import PredictionModel, Predictor

__all__ = [
    "OpLogisticRegression", "OpLinearSVC", "OpLinearRegression",
    "LinearClassificationModel", "LinearRegressionModel",
]


# ---------------------------------------------------------------------------
# shared trainer
# ---------------------------------------------------------------------------

def _standardize_stats(X, w):
    wsum = jnp.maximum(jnp.sum(w), 1.0)
    mu = jnp.sum(X * w[:, None], axis=0) / wsum
    var = jnp.sum(((X - mu) ** 2) * w[:, None], axis=0) / wsum
    sd = jnp.sqrt(jnp.maximum(var, 1e-12))
    sd = jnp.where(sd < 1e-6, 1.0, sd)
    return mu, sd


def _linear_fit_space(X, y, w, *, loss_kind: str, fit_intercept: bool,
                      standardize: bool):
    """Shared preamble: standardized features/target and the fold-back
    statistics. Squared loss trains against the STANDARDIZED target —
    Adam(0.1) x max_iter steps can only travel ~max_iter/10 from 0, so
    raw targets with large mean OR large scale (Boston medv ~22, dollar
    prices ~1e5) silently under-fit; in (y - ym)/ysd space the optimum
    is O(1) in every direction. Classification is untouched (margins
    live near 0 already)."""
    n, d = X.shape
    if standardize:
        mu, sd = _standardize_stats(X, w)
        Xs = (X - mu) / sd
    else:
        mu, sd = jnp.zeros(d), jnp.ones(d)
        Xs = X
    wsum = jnp.maximum(jnp.sum(w), 1.0)
    if loss_kind == "squared" and fit_intercept:
        ym = jnp.sum(y * w) / wsum
        ysd = jnp.sqrt(jnp.maximum(
            jnp.sum(((y - ym) ** 2) * w) / wsum, 1e-12))
        y_fit = (y - ym) / ysd
    else:
        ym, ysd = jnp.float32(0.0), jnp.float32(1.0)
        y_fit = y
    return Xs, y_fit, mu, sd, ym, ysd, wsum


def _linear_descent(Xs, y, y_fit, w, wsum, reg_param, elastic_net, W0, b0,
                    *, loss_kind: str, max_iter: int, fit_intercept: bool):
    """The Adam descent from an explicit fit-space init (shared by the
    cold ``_train_linear`` and the warm-started refit program)."""
    n = Xs.shape[0]

    def objective(params):
        W, b = params
        z = Xs @ W + b
        if loss_kind == "softmax":
            logp = jax.nn.log_softmax(z, axis=-1)
            nll = -logp[jnp.arange(n), y.astype(jnp.int32)]
            data_loss = jnp.sum(nll * w) / wsum
        elif loss_kind == "hinge":
            s = 2.0 * y_fit - 1.0
            margin = jnp.maximum(0.0, 1.0 - s * z[:, 0])
            data_loss = jnp.sum(margin * w) / wsum
        else:  # squared (y_fit is the standardized target)
            data_loss = 0.5 * jnp.sum(((z[:, 0] - y_fit) ** 2) * w) / wsum
        l2 = 0.5 * jnp.sum(W ** 2)
        l1 = jnp.sum(jnp.abs(W))
        return data_loss + reg_param * ((1.0 - elastic_net) * l2
                                        + elastic_net * l1)

    opt = optax.adam(0.1)
    state0 = opt.init((W0, b0))

    def step(carry, _):
        params, opt_state = carry
        loss, grads = jax.value_and_grad(objective)(params)
        if not fit_intercept:
            grads = (grads[0], jnp.zeros_like(grads[1]))
        updates, opt_state = opt.update(grads, opt_state)
        params = optax.apply_updates(params, updates)
        return (params, opt_state), loss

    (params, _), losses = jax.lax.scan(step, ((W0, b0), state0), None,
                                       length=max_iter)
    return params[0], params[1], losses[-1]


@functools.partial(jax.jit, static_argnames=("loss_kind", "n_classes",
                                             "max_iter", "fit_intercept",
                                             "standardize"))
def _train_linear(X, y, w, reg_param, elastic_net, *, loss_kind: str,
                  n_classes: int, max_iter: int, fit_intercept: bool,
                  standardize: bool):
    """One linear training run. reg_param/elastic_net are traced scalars so
    the same compiled program serves every grid point (and vmaps)."""
    d = X.shape[1]
    Xs, y_fit, mu, sd, ym, ysd, wsum = _linear_fit_space(
        X, y, w, loss_kind=loss_kind, fit_intercept=fit_intercept,
        standardize=standardize)
    C = n_classes if loss_kind == "softmax" else 1
    W0 = jnp.zeros((d, C), dtype=jnp.float32)
    b0 = jnp.zeros((C,), dtype=jnp.float32)
    W, b, last_loss = _linear_descent(
        Xs, y, y_fit, w, wsum, reg_param, elastic_net, W0, b0,
        loss_kind=loss_kind, max_iter=max_iter, fit_intercept=fit_intercept)
    # fold target standardization (squared loss) then feature
    # standardization back into original space
    W = W * ysd
    b = b * ysd + ym
    W_orig = W / sd[:, None]
    b_orig = b - (mu / sd) @ W
    return W_orig, b_orig, last_loss


def _train_linear_from(X, y, w, reg_param, elastic_net, W_init, b_init, *,
                       loss_kind: str, max_iter: int, fit_intercept: bool,
                       standardize: bool):
    """Warm-started linear refit (round 9): same descent as
    ``_train_linear`` but initialized from ``W_init``/``b_init`` given in
    ORIGINAL feature space (what the stacked fold parameters are in after
    fold-back) — the init maps into fit space with the refit data's own
    standardization statistics. Compiled via ``compile_refit`` with the
    init buffers donated (they are dead once consumed)."""
    Xs, y_fit, mu, sd, ym, ysd, wsum = _linear_fit_space(
        X, y, w, loss_kind=loss_kind, fit_intercept=fit_intercept,
        standardize=standardize)
    # inverse of the fold-back at the bottom of _train_linear
    W0 = W_init * sd[:, None] / ysd
    b0 = (b_init + mu @ W_init - ym) / ysd
    W, b, last_loss = _linear_descent(
        Xs, y, y_fit, w, wsum, reg_param, elastic_net, W0, b0,
        loss_kind=loss_kind, max_iter=max_iter, fit_intercept=fit_intercept)
    W = W * ysd
    b = b * ysd + ym
    W_orig = W / sd[:, None]
    b_orig = b - (mu / sd) @ W
    return W_orig, b_orig, last_loss


_WARM_PROGRAM = None  # lazily compiled (backend known only at first use)


def _linear_warm_program():
    """The donated-buffer compiled warm-refit program (SNIPPETS [1]'s
    ``donate_argnums`` compile-helper pattern): argnums 5/6 are the
    W/b init arrays, consumed exactly once."""
    global _WARM_PROGRAM
    if _WARM_PROGRAM is None:
        from transmogrifai_tpu.models.base import compile_refit
        _WARM_PROGRAM = compile_refit(
            _train_linear_from, donate_argnums=(5, 6),
            static_argnames=("loss_kind", "max_iter", "fit_intercept",
                             "standardize"))
    return _WARM_PROGRAM


@functools.partial(jax.jit, static_argnames=("n_iter", "fit_intercept",
                                             "standardize"))
def _train_logistic_newton(X, y, w, reg_param, *, n_iter: int = 15,
                           fit_intercept: bool, standardize: bool):
    """Binary L2 logistic via damped Newton/IRLS — the workhorse grid
    points (elastic_net=0) converge in ~10 steps instead of hundreds of
    first-order ones; each step is two MXU matmuls (X^T R X, X^T r) and a
    [d+1,d+1] solve. Spark's LR uses L-BFGS for the same reason; Newton is
    the TPU-friendly second-order choice because the Hessian build is a
    matmul.

    Trained in margin space u (z = Xs @ u + b); returns the equivalent
    2-column softmax weights so outputs match ``_train_linear`` exactly.
    """
    n, d = X.shape
    if standardize:
        mu, sd = _standardize_stats(X, w)
        Xs = (X - mu) / sd
    else:
        mu, sd = jnp.zeros(d), jnp.ones(d)
        Xs = X
    wsum = jnp.maximum(jnp.sum(w), 1.0)
    # softmax-space penalty reg*0.5*||W||^2 with W=[-u/2, u/2] equals
    # margin-space 0.5*(reg/2)*||u||^2
    lam = reg_param * 0.5
    Xb = jnp.concatenate([Xs, jnp.ones((n, 1), Xs.dtype)], axis=1)

    penalty_mask = jnp.ones(d + 1).at[-1].set(0.0)  # intercept unpenalized

    def step(uv, _):
        z = Xb @ uv
        p = jax.nn.sigmoid(z)
        r = w * (p - y) / wsum
        R = w * jnp.maximum(p * (1.0 - p), 1e-6) / wsum
        g = Xb.T @ r + lam * penalty_mask * uv
        H = (Xb * R[:, None]).T @ Xb
        # Levenberg damping sized to the problem: with reg_param=0 a
        # perfectly collinear one-hot block makes H singular and a 1e-8
        # ridge amplifies float32 noise to NaN within a few iterations
        H = H + jnp.diag(lam * penalty_mask + 1e-4)
        delta = jax.scipy.linalg.solve(H, g, assume_a="pos")
        if not fit_intercept:
            delta = delta.at[-1].set(0.0)
        # a non-finite step (defective solve) must not poison the carry —
        # keep the previous iterate instead
        new = uv - delta
        return jnp.where(jnp.all(jnp.isfinite(new)), new, uv), 0.0

    uv0 = jnp.zeros(d + 1, jnp.float32)
    uv, _ = jax.lax.scan(step, uv0, None, length=n_iter)
    u, bu = uv[:d], uv[d]
    # margin space -> equivalent 2-column softmax weights, unstandardized
    W = jnp.stack([-u / 2.0, u / 2.0], axis=1) / sd[:, None]
    b = jnp.stack([-bu / 2.0, bu / 2.0])
    b = b - (mu / sd) @ jnp.stack([-u / 2.0, u / 2.0], axis=1)
    return W, b, jnp.float32(0.0)


def _shard_candidates(*arrs):
    """Shard the leading (candidate/grid) axis over the mesh "model" axis
    when one is active — the grid sweep then runs 2-D parallel: rows over
    "data" (X is row-sharded), candidates over "model" (SURVEY §2.7 P3)."""
    from transmogrifai_tpu.parallel import mesh as pmesh
    ctx = pmesh.current_mesh()
    if ctx is None or ctx.n_model <= 1 or arrs[0].shape[0] % ctx.n_model:
        return arrs
    return tuple(jax.device_put(a, ctx.model_sharding(
        *([None] * (a.ndim - 1)))) for a in arrs)


def _run_grid(X, y, w, grid: Sequence[dict], defaults: dict, kw: dict):
    """Train the whole grid as one stacked-axis vmapped program. Static
    config (max_iter etc.) must agree across the grid; the regularization
    scalars are the batched axes."""
    from transmogrifai_tpu.utils import flops
    rp = jnp.asarray([float({**defaults, **g}["reg_param"]) for g in grid],
                     jnp.float32)
    en = jnp.asarray([float({**defaults, **g}["elastic_net_param"]) for g in grid],
                     jnp.float32)
    rp, en = _shard_candidates(rp, en)
    f = jax.vmap(lambda r, e: _train_linear(X, y, w, r, e, **kw))
    n, d = X.shape
    C = kw["n_classes"] if kw["loss_kind"] == "softmax" else 1
    # per Adam step: forward z = X@W (2ndC) + backward grads (~4ndC)
    flops.add("linear", len(grid) * kw["max_iter"] * 6.0 * n * d * C)
    return f(rp, en)


def _run_grid_folds(Xf, yf, wf, grid: Sequence[dict], defaults: dict,
                    kw: dict):
    """Fold-stacked grid trainer: ``Xf [k, n, d]`` — all k folds x |grid|
    Adam descents as ONE vmap-of-vmap program (the CV axis joins the grid
    axis, so a whole family's sweep is a single dispatch). The grid scalars
    shard over the mesh "model" axis only when the fold axis doesn't claim
    it (``shard_stacked_training_rows`` already placed the folds)."""
    from transmogrifai_tpu.parallel import mesh as pmesh
    from transmogrifai_tpu.utils import flops
    rp = jnp.asarray([float({**defaults, **g}["reg_param"]) for g in grid],
                     jnp.float32)
    en = jnp.asarray([float({**defaults, **g}["elastic_net_param"])
                      for g in grid], jnp.float32)
    if not pmesh.fold_axis_on_model(int(Xf.shape[0])):
        rp, en = _shard_candidates(rp, en)
    inner = lambda Xk, yk, wk: jax.vmap(  # noqa: E731 — vmap composition
        lambda r, e: _train_linear(Xk, yk, wk, r, e, **kw))(rp, en)
    k, n, d = Xf.shape
    C = kw["n_classes"] if kw["loss_kind"] == "softmax" else 1
    flops.add("linear",
              int(k) * len(grid) * kw["max_iter"] * 6.0 * int(n) * int(d) * C)
    return jax.vmap(inner)(Xf, yf, wf)  # Ws [k, G, d, C], bs [k, G, C]


def _merge_grid_parts(parts, order):
    """Reassemble per-static-group stacked params ``[(Ws [k, g_i, d, C],
    bs [k, g_i, C]), ...]`` into grid order along the grid axis."""
    if len(parts) == 1:
        Ws, bs = parts[0]
    else:
        Ws = jnp.concatenate([p[0] for p in parts], axis=1)
        bs = jnp.concatenate([p[1] for p in parts], axis=1)
    if list(order) != sorted(order):
        inv = jnp.asarray(np.argsort(np.asarray(order)))
        Ws, bs = Ws[:, inv], bs[:, inv]
    return Ws, bs


# ---------------------------------------------------------------------------
# fitted models
# ---------------------------------------------------------------------------

class LinearClassificationModel(PredictionModel):
    """argmax over class logits; binary emits 2-class raw/probability."""

    def __init__(self, weights=None, intercept=None, probabilistic: bool = True,
                 uid: Optional[str] = None):
        # weights may be device arrays during the CV sweep (no host pull);
        # they convert lazily on serialization/introspection
        self.weights = weights if weights is not None else np.zeros((0, 2))
        self.intercept = intercept if intercept is not None else np.zeros(2)
        self.probabilistic = probabilistic
        super().__init__(uid=uid)

    def device_params(self):
        return (jnp.asarray(self.weights, jnp.float32),
                jnp.asarray(self.intercept, jnp.float32))

    def quantize_device_params(self, precision):
        if precision != "int8":
            return None
        from transmogrifai_tpu.utils.precision import quantize_weights
        W, b = self.device_params()
        return (quantize_weights(W), b)

    def device_apply(self, params, col: fr.VectorColumn) -> fr.PredictionColumn:
        W, b = params
        z = col.values @ W + b
        if z.shape[1] == 1:  # margin-only binary (SVC)
            z = jnp.concatenate([-z, z], axis=1)
        prob = jax.nn.softmax(z, axis=-1) if self.probabilistic \
            else jax.nn.one_hot(jnp.argmax(z, axis=-1), z.shape[1])
        pred = jnp.argmax(z, axis=-1).astype(jnp.float32)
        return fr.PredictionColumn(pred, z, prob)

    def fitted_state(self):
        return {"weights": np.asarray(self.weights, np.float64),
                "intercept": np.asarray(self.intercept, np.float64),
                "probabilistic": self.probabilistic}

    def set_fitted_state(self, state):
        self.weights = np.asarray(state["weights"], np.float64)
        self.intercept = np.asarray(state["intercept"], np.float64)
        self.probabilistic = bool(state.get("probabilistic", True))

    def config(self):
        return {"probabilistic": self.probabilistic}

    @classmethod
    def from_config(cls, config, uid=None):
        return cls(probabilistic=config.get("probabilistic", True), uid=uid)

    def feature_contributions(self) -> np.ndarray:
        """Per-feature coefficients (binary: positive-class column) for
        ModelInsights."""
        W = np.asarray(self.weights)
        return W[:, -1] if W.shape[1] >= 2 else W[:, 0]


class LinearRegressionModel(PredictionModel):
    def __init__(self, weights=None, intercept=0.0,
                 uid: Optional[str] = None):
        self.weights = weights if weights is not None else np.zeros(0)
        self.intercept = intercept
        super().__init__(uid=uid)

    def device_params(self):
        return (jnp.asarray(self.weights, jnp.float32),
                jnp.asarray(self.intercept, jnp.float32))

    def quantize_device_params(self, precision):
        if precision != "int8":
            return None
        from transmogrifai_tpu.utils.precision import quantize_weights
        W, b = self.device_params()
        return (quantize_weights(W), b)

    def device_apply(self, params, col: fr.VectorColumn) -> fr.PredictionColumn:
        W, b = params
        yhat = col.values @ W + b
        n = yhat.shape[0]
        empty = jnp.zeros((n, 0), jnp.float32)
        return fr.PredictionColumn(yhat, empty, empty)

    def fitted_state(self):
        return {"weights": np.asarray(self.weights, np.float64),
                "intercept": np.float64(self.intercept)}

    def set_fitted_state(self, state):
        self.weights = np.asarray(state["weights"], np.float64)
        self.intercept = float(state["intercept"])

    def config(self):
        return {}

    @classmethod
    def from_config(cls, config, uid=None):
        return cls(uid=uid)

    def feature_contributions(self) -> np.ndarray:
        return np.asarray(self.weights)


# ---------------------------------------------------------------------------
# estimators
# ---------------------------------------------------------------------------

class _LinearPredictor(Predictor):
    loss_kind = "softmax"
    probabilistic = True

    default_params = {
        "reg_param": 0.0,
        "elastic_net_param": 0.0,
        "max_iter": 200,
        "fit_intercept": True,
        "standardization": True,
        "tol": 1e-6,
    }

    def _static_kw(self, params, n_classes: int) -> dict:
        return dict(loss_kind=self.loss_kind, n_classes=n_classes,
                    max_iter=int(params["max_iter"]),
                    fit_intercept=bool(params["fit_intercept"]),
                    standardize=bool(params["standardization"]))

    def _n_classes(self, y) -> int:
        if self.loss_kind != "softmax":
            return 2
        return max(int(np.asarray(jnp.max(y))) + 1, 2)

    def _make_model(self, W, b) -> PredictionModel:
        # W/b stay device-resident; host conversion happens lazily
        if self.loss_kind == "squared":
            return LinearRegressionModel(weights=W[:, 0], intercept=b[0])
        return LinearClassificationModel(
            weights=W, intercept=b, probabilistic=self.probabilistic)

    def fit_arrays(self, X, y, w, params):
        kw = self._static_kw(params, self._n_classes(y))
        W, b, _ = _train_linear(
            X, y, w, jnp.float32(params["reg_param"]),
            jnp.float32(params["elastic_net_param"]), **kw)
        return self._make_model(W, b)

    def grid_fit_arrays(self, X, y, w, grid):
        if not grid:
            return []
        # group grid points by their static flags (max_iter/intercept/
        # standardization are compile-time constants): one vmapped program
        # per distinct combo, so a mixed grid never silently trains with
        # another point's flags
        merged = [{**self.params, **g} for g in grid]
        models: list = [None] * len(grid)
        by_kw: dict[tuple, list[int]] = {}
        for i, g in enumerate(merged):
            key = (int(g["max_iter"]), bool(g["fit_intercept"]),
                   bool(g["standardization"]))
            by_kw.setdefault(key, []).append(i)
        for idxs in by_kw.values():
            kw = self._static_kw(merged[idxs[0]], self._n_classes(y))
            Ws, bs, _ = _run_grid(X, y, w, [grid[i] for i in idxs],
                                  self.params, kw)
            # keep per-model weights as device views — no host pull in sweep
            for j, i in enumerate(idxs):
                models[i] = self._make_model(Ws[j], bs[j])
        return models

    def grid_predict_scores(self, models, X):
        """All grid candidates score in one einsum: [G, n] margins
        (classification) or predictions (regression)."""
        if not models:
            return None
        W = jnp.stack([jnp.asarray(m.weights, jnp.float32) for m in models])
        b = jnp.stack([jnp.asarray(m.intercept, jnp.float32) for m in models])
        if self.loss_kind == "squared":
            return jnp.einsum("nd,gd->gn", X, W) + b[:, None]
        z = jnp.einsum("nd,gdc->gnc", X, W) + b[:, None, :]
        if z.shape[-1] == 1:       # margin-only (SVC)
            return z[:, :, 0]
        if z.shape[-1] == 2:       # binary margin
            return z[:, :, 1] - z[:, :, 0]
        return None                # multiclass: no scalar score

    def _grid_n_classes(self, y, _n_classes=None) -> int:
        """The family's class count for a stacked sweep batch: the
        selector's once-per-sweep hint when given (saves the per-family
        blocking ``max(y)`` pull on the one-sync dispatch path — only
        softmax families ever paid it), else the family's own probe.
        The hint is computed from the SAME stacked label batch with the
        same expression, so both routes agree exactly."""
        if _n_classes is not None and self.loss_kind == "softmax":
            return int(_n_classes)
        return self._n_classes(y)

    def _fold_stacked_params_gated(self, X, y, w, grid, _n_classes=None):
        """Call ``_fold_stacked_params`` threading ``_n_classes`` only when
        the (possibly subclass-overridden) signature accepts it — same gate
        as ``Predictor.grid_scores_folds``, so pre-round-9 overrides with
        the old arity keep working."""
        import inspect
        kw = {}
        if _n_classes is not None and "_n_classes" in \
                inspect.signature(self._fold_stacked_params).parameters:
            kw["_n_classes"] = _n_classes
        return self._fold_stacked_params(X, y, w, grid, **kw)

    # -- fold-stacked sweep --------------------------------------------------
    def _fold_stacked_params(self, X, y, w, grid, _n_classes=None):
        """All k folds x |grid| points in one vmapped program per distinct
        static-flag combo; returns the stacked ``(Ws [k, G, d, C],
        bs [k, G, C])`` in grid order (device-resident)."""
        merged = [{**self.params, **g} for g in grid]
        by_kw: dict[tuple, list[int]] = {}
        for i, g in enumerate(merged):
            key = (int(g["max_iter"]), bool(g["fit_intercept"]),
                   bool(g["standardization"]))
            by_kw.setdefault(key, []).append(i)
        parts, order = [], []
        n_classes = self._grid_n_classes(y, _n_classes)
        for idxs in by_kw.values():
            kw = self._static_kw(merged[idxs[0]], n_classes)
            Ws, bs, _ = _run_grid_folds(X, y, w, [grid[i] for i in idxs],
                                        self.params, kw)
            parts.append((Ws, bs))
            order.extend(idxs)
        return _merge_grid_parts(parts, order)

    def grid_fit_arrays_folds(self, X, y, w, grid):
        """``[k][G]`` fitted models whose weights stay device views of the
        stacked result (no host pull in the sweep)."""
        if not grid:
            return []
        Ws, bs = self._fold_stacked_params(X, y, w, grid)
        return [[self._make_model(Ws[f, j], bs[f, j])
                 for j in range(len(grid))] for f in range(int(X.shape[0]))]

    def _scores_from_stacked(self, Ws, bs, Xva):
        """[k, G, n_va] scores straight from stacked parameters."""
        if self.loss_kind == "squared":
            return jnp.einsum("knd,kgd->kgn", Xva, Ws[..., 0]) \
                + bs[..., 0][:, :, None]
        z = jnp.einsum("knd,kgdc->kgnc", Xva, Ws) + bs[:, :, None, :]
        if z.shape[-1] == 1:       # margin-only (SVC)
            return z[..., 0]
        if z.shape[-1] == 2:       # binary margin
            return z[..., 1] - z[..., 0]
        return None                # multiclass: no scalar score

    def grid_scores_folds(self, X, y, w, grid, Xva, _n_classes=None):
        """Fused sweep unit: stacked parameters -> stacked scores with no
        per-(fold, grid) model materialization in between."""
        if not grid:
            return None
        Ws, bs = self._fold_stacked_params_gated(X, y, w, grid,
                                                 _n_classes=_n_classes)
        return self._scores_from_stacked(Ws, bs, Xva)

    def grid_scores_folds_retained(self, X, y, w, grid, Xva,
                                   _n_classes=None):
        """One-sync dispatch unit: stacked scores PLUS the stacked fold
        parameters ``(Ws [k, G, d, C], bs [k, G, C])`` retained as the
        winner refit's warm-start handle (device views — the arrays
        already exist; retaining them just extends their lifetime to the
        refit). A subclass overriding ``grid_scores_folds`` itself keeps
        its semantics: delegate there (no warm handle) instead of
        silently bypassing the override with the fused body."""
        if type(self).grid_scores_folds is not \
                _LinearPredictor.grid_scores_folds:
            return super().grid_scores_folds_retained(
                X, y, w, grid, Xva, _n_classes=_n_classes)
        if not grid:
            return None, None
        Ws, bs = self._fold_stacked_params_gated(X, y, w, grid,
                                                 _n_classes=_n_classes)
        scores = self._scores_from_stacked(Ws, bs, Xva)
        if scores is None:
            return None, None
        return scores, (Ws, bs)

    # -- warm winner refit (round 9) -----------------------------------------
    def supports_warm_refit(self) -> bool:
        return True

    def refit_winner(self, X, y, w, params, *, warm=None, lane=None,
                     hints=None):
        """Full-data winner refit. With a ``warm`` handle (the sweep's
        stacked fold parameters) the Adam descent initializes from the
        fold-AVERAGED winning-lane parameters — a near-optimum start for
        the convex losses — through the donated-buffer compiled program
        (``_linear_warm_program``); the grid's G-1 losing lanes and the
        fold axis collapse, so this is the stacked machinery at G=1.
        Without one (loop-path sweeps, gating off) the refit is the exact
        cold ``fit_arrays`` the serial path always ran."""
        p = {**self.params, **params}
        if warm is None or lane is None:
            return self.fit_arrays(X, y, w, p), False
        Ws, bs = warm
        W_init = jnp.mean(jnp.asarray(Ws, jnp.float32)[:, int(lane)],
                          axis=0)
        b_init = jnp.mean(jnp.asarray(bs, jnp.float32)[:, int(lane)],
                          axis=0)
        kw = self._static_kw(p, self._n_classes(y))
        kw.pop("n_classes")
        W, b, _ = _linear_warm_program()(
            X, y, w, jnp.float32(p["reg_param"]),
            jnp.float32(p["elastic_net_param"]), W_init, b_init, **kw)
        return self._make_model(W, b), True

    def grid_predict_scores_folds(self, models, X):
        """[k, G, n_va] validation scores in one einsum over the stacked
        fold axis — the selector computes every fold's metrics from this
        with a single host sync per family."""
        if not models or not models[0]:
            return None
        W = jnp.stack([jnp.stack([jnp.asarray(m.weights, jnp.float32)
                                  for m in row]) for row in models])
        b = jnp.stack([jnp.stack([jnp.asarray(m.intercept, jnp.float32)
                                  for m in row]) for row in models])
        if self.loss_kind == "squared":
            return jnp.einsum("knd,kgd->kgn", X, W) + b[:, :, None]
        z = jnp.einsum("knd,kgdc->kgnc", X, W) + b[:, :, None, :]
        if z.shape[-1] == 1:       # margin-only (SVC)
            return z[..., 0]
        if z.shape[-1] == 2:       # binary margin
            return z[..., 1] - z[..., 0]
        return None                # multiclass: no scalar score


class OpLogisticRegression(_LinearPredictor):
    """Multinomial/binary logistic regression (softmax NLL + elastic net).

    Binary L2-only fits (elastic_net_param=0, the AutoML default grid's
    workhorse) take the Newton/IRLS fast path — ~15 second-order steps
    instead of ``max_iter`` first-order ones; L1 points and multiclass stay
    on the Adam path. Capped at ``_NEWTON_MAX_D`` features (the Hessian is
    [d+1, d+1]).
    """

    loss_kind = "softmax"
    probabilistic = True

    _NEWTON_MAX_D = 2048

    def _newton_ok(self, params, d: int, n_classes: int) -> bool:
        return (float(params.get("elastic_net_param", 0.0)) == 0.0
                and int(d) <= self._NEWTON_MAX_D
                and n_classes == 2)

    def fit_arrays(self, X, y, w, params):
        params = {**self.params, **params}
        if self._newton_ok(params, X.shape[1], self._n_classes(y)):
            W, b, _ = _train_logistic_newton(
                X, y, w, jnp.float32(params["reg_param"]),
                fit_intercept=bool(params["fit_intercept"]),
                standardize=bool(params["standardization"]))
            return self._make_model(W, b)
        return super().fit_arrays(X, y, w, params)

    def grid_fit_arrays(self, X, y, w, grid):
        if not grid:
            return []
        merged = [{**self.params, **g} for g in grid]
        n_classes = self._n_classes(y)  # ONE device sync for the whole grid
        newton_idx = [i for i, g in enumerate(merged)
                      if self._newton_ok(g, X.shape[1], n_classes)]
        if not newton_idx:
            return super().grid_fit_arrays(X, y, w, grid)
        adam_idx = [i for i in range(len(grid)) if i not in set(newton_idx)]
        models: list = [None] * len(grid)
        # Newton points vmapped over reg_param, one program per distinct
        # (fit_intercept, standardization) combo — those flags are static
        # and must not silently inherit the first grid point's values
        by_flags: dict[tuple[bool, bool], list[int]] = {}
        for i in newton_idx:
            key = (bool(merged[i]["fit_intercept"]),
                   bool(merged[i]["standardization"]))
            by_flags.setdefault(key, []).append(i)
        for (fit_b, std_b), idxs in by_flags.items():
            rp = jnp.asarray([merged[i]["reg_param"] for i in idxs],
                             jnp.float32)
            rp, = _shard_candidates(rp)
            Ws, bs, _ = jax.vmap(lambda r: _train_logistic_newton(
                X, y, w, r, fit_intercept=fit_b, standardize=std_b))(rp)
            from transmogrifai_tpu.utils import flops
            n, d = X.shape
            # per Newton step: z/grad matvecs 4n(d+1) + Hessian build
            # 2n(d+1)^2 + dense solve (2/3)(d+1)^3
            flops.add("linear", len(idxs) * 15 * (
                4.0 * n * (d + 1) + 2.0 * n * (d + 1) ** 2
                + (2.0 / 3.0) * (d + 1) ** 3))
            for j, i in enumerate(idxs):
                models[i] = self._make_model(Ws[j], bs[j])
        if adam_idx:
            rest = super().grid_fit_arrays(X, y, w,
                                           [grid[i] for i in adam_idx])
            for j, i in enumerate(adam_idx):
                models[i] = rest[j]
        return models

    def _fold_stacked_params(self, X, y, w, grid, _n_classes=None):
        """Fold-stacked LR sweep: the Newton points vmap over (fold x
        reg_param) — one second-order program for the whole family's
        workhorse grid across every fold — and the L1/multiclass rest rides
        the fold-stacked Adam path. Same point-by-point routing as the
        per-fold ``grid_fit_arrays``, so both paths pick identical
        optimizers for every grid point (sweep-parity requirement)."""
        from transmogrifai_tpu.parallel import mesh as pmesh
        merged = [{**self.params, **g} for g in grid]
        # ONE device sync for the family, elided by the selector's hint
        n_classes = self._grid_n_classes(y, _n_classes)
        d = int(X.shape[2])
        k = int(X.shape[0])
        newton_idx = [i for i, g in enumerate(merged)
                      if self._newton_ok(g, d, n_classes)]
        if not newton_idx:
            return super()._fold_stacked_params(X, y, w, grid,
                                                _n_classes=n_classes)
        adam_idx = [i for i in range(len(grid)) if i not in set(newton_idx)]
        parts, order = [], []
        by_flags: dict[tuple[bool, bool], list[int]] = {}
        for i in newton_idx:
            key = (bool(merged[i]["fit_intercept"]),
                   bool(merged[i]["standardization"]))
            by_flags.setdefault(key, []).append(i)
        for (fit_b, std_b), idxs in by_flags.items():
            rp = jnp.asarray([merged[i]["reg_param"] for i in idxs],
                             jnp.float32)
            if not pmesh.fold_axis_on_model(k):
                rp, = _shard_candidates(rp)
            inner = lambda Xk, yk, wk: jax.vmap(  # noqa: E731
                lambda r: _train_logistic_newton(
                    Xk, yk, wk, r, fit_intercept=fit_b,
                    standardize=std_b))(rp)
            Ws, bs, _ = jax.vmap(inner)(X, y, w)  # [k, g, ...]
            from transmogrifai_tpu.utils import flops
            n = int(X.shape[1])
            flops.add("linear", k * len(idxs) * 15 * (
                4.0 * n * (d + 1) + 2.0 * n * (d + 1) ** 2
                + (2.0 / 3.0) * (d + 1) ** 3))
            parts.append((Ws, bs))
            order.extend(idxs)
        if adam_idx:
            parts.append(super()._fold_stacked_params(
                X, y, w, [grid[i] for i in adam_idx],
                _n_classes=n_classes))
            order.extend(adam_idx)
        return _merge_grid_parts(parts, order)

    def refit_winner(self, X, y, w, params, *, warm=None, lane=None,
                     hints=None):
        """Newton-eligible winners (binary pure-L2, the workhorse grid)
        refit COLD: ~15 damped second-order steps converge from zero
        regardless of init, so the cold path keeps the serial refit's
        bitwise result for free. Only Adam-path winners (L1 points) use
        the warm-started descent."""
        p = {**self.params, **params}
        if self._newton_ok(p, X.shape[1], self._n_classes(y)):
            return self.fit_arrays(X, y, w, p), False
        return super().refit_winner(X, y, w, params, warm=warm, lane=lane,
                                    hints=hints)


class OpLinearSVC(_LinearPredictor):
    """Linear SVM (hinge loss); emits margins, probabilities via one-hot."""
    loss_kind = "hinge"
    probabilistic = False


class OpLinearRegression(_LinearPredictor):
    """Least squares + elastic net."""
    loss_kind = "squared"
    probabilistic = False
