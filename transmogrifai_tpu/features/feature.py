"""The lazy, typed feature graph.

Parity: reference ``features/src/main/scala/com/salesforce/op/features/
{FeatureLike,Feature,TransientFeature}.scala`` — a Feature is a typed, lazy
pointer to a future column: name, uid, response flag, origin stage and parent
features. Equality is by origin-stage uid + parents. ``transform_with`` wires
a stage into the graph and returns its output feature; the workflow later
back-traces lineage from result features to compile the stage DAG.
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Iterable, Optional

from transmogrifai_tpu.types import feature_types as ft

if TYPE_CHECKING:
    from transmogrifai_tpu.stages.base import PipelineStage

__all__ = ["FeatureLike", "Feature", "TransientFeature"]


class FeatureLike:
    """A typed node in the feature graph."""

    def __init__(self, name: str, uid: str, ftype: type[ft.FeatureType],
                 origin_stage: "PipelineStage",
                 parents: tuple["FeatureLike", ...] = (),
                 is_response: bool = False):
        self._name = name
        self._uid = uid
        self._ftype = ftype
        self._origin_stage = origin_stage
        self._parents = tuple(parents)
        self._is_response = is_response

    # -- identity ------------------------------------------------------------
    @property
    def name(self) -> str:
        return self._name

    @property
    def uid(self) -> str:
        return self._uid

    @property
    def ftype(self) -> type[ft.FeatureType]:
        return self._ftype

    @property
    def origin_stage(self) -> "PipelineStage":
        return self._origin_stage

    @property
    def parents(self) -> tuple["FeatureLike", ...]:
        return self._parents

    @property
    def is_response(self) -> bool:
        return self._is_response

    @property
    def is_raw(self) -> bool:
        return len(self._parents) == 0

    def __eq__(self, other) -> bool:
        if not isinstance(other, FeatureLike):
            return NotImplemented
        return (self._origin_stage.uid == other._origin_stage.uid
                and self._name == other._name
                and tuple(p.uid for p in self._parents)
                == tuple(p.uid for p in other._parents))

    def __hash__(self) -> int:
        return hash((self._origin_stage.uid, self._name,
                     tuple(p.uid for p in self._parents)))

    def __repr__(self) -> str:
        kind = "response" if self._is_response else "predictor"
        return (f"Feature[{self._ftype.__name__}]({self._name!r}, {kind}, "
                f"origin={self._origin_stage.uid})")

    # -- graph construction --------------------------------------------------
    def transform_with(self, stage: "PipelineStage",
                       *others: "FeatureLike") -> "FeatureLike":
        """Apply a stage to this feature (+ additional inputs); returns the
        stage's output feature (reference FeatureLike.transformWith)."""
        stage.set_input(self, *others)
        return stage.get_output()

    # -- graph traversal -----------------------------------------------------
    def parent_stages(self) -> dict["PipelineStage", int]:
        """All ancestor stages with their max distance from this feature
        (reference FeatureLike.parentStages via scala-graph; plain BFS here).
        Distance 0 = this feature's origin stage."""
        dist: dict[PipelineStage, int] = {}

        def visit(feat: "FeatureLike", d: int) -> None:
            stage = feat.origin_stage
            if stage is None:
                return
            if stage in dist and dist[stage] >= d:
                return  # parents already propagated at >= d+1
            dist[stage] = d
            for p in feat.parents:
                visit(p, d + 1)

        visit(self, 0)
        return dist

    def raw_features(self) -> list["FeatureLike"]:
        """All raw ancestors (deduped, stable order)."""
        seen: dict[str, FeatureLike] = {}

        def walk(f: "FeatureLike"):
            if f.is_raw:
                seen.setdefault(f.uid, f)
            for p in f.parents:
                walk(p)

        walk(self)
        return list(seen.values())

    def all_features(self) -> list["FeatureLike"]:
        seen: dict[str, FeatureLike] = {}

        def walk(f: "FeatureLike"):
            if f.uid not in seen:
                seen[f.uid] = f
                for p in f.parents:
                    walk(p)

        walk(self)
        return list(seen.values())

    def history(self) -> dict:
        """Originating raw features + stage operation names along the lineage
        (reference FeatureHistory)."""
        return {
            "originFeatures": sorted(f.name for f in self.raw_features()),
            "stages": sorted({s.operation_name for s in self.parent_stages()
                              if not s.is_raw_generator}),
        }

    def to_transient(self) -> "TransientFeature":
        return TransientFeature(
            name=self._name, uid=self._uid, ftype_name=self._ftype.__name__,
            is_response=self._is_response, is_raw=self.is_raw,
            origin_stage_uid=self._origin_stage.uid,
            parent_uids=tuple(p.uid for p in self._parents),
        )


class Feature(FeatureLike):
    """Concrete feature (the reference splits interface/case-class; we keep
    the split nominal)."""


class TransientFeature:
    """Serialization-safe feature reference that drops the DAG pointer
    (reference TransientFeature.scala) — what stages persist."""

    def __init__(self, name: str, uid: str, ftype_name: str, is_response: bool,
                 is_raw: bool, origin_stage_uid: str,
                 parent_uids: tuple[str, ...] = ()):
        self.name = name
        self.uid = uid
        self.ftype_name = ftype_name
        self.is_response = is_response
        self.is_raw = is_raw
        self.origin_stage_uid = origin_stage_uid
        self.parent_uids = tuple(parent_uids)

    @property
    def ftype(self) -> type[ft.FeatureType]:
        return ft.feature_type_of(self.ftype_name)

    def to_json(self) -> dict:
        return {
            "name": self.name, "uid": self.uid, "typeName": self.ftype_name,
            "isResponse": self.is_response, "isRaw": self.is_raw,
            "originStage": self.origin_stage_uid,
            "parents": list(self.parent_uids),
        }

    @staticmethod
    def from_json(d: dict) -> "TransientFeature":
        return TransientFeature(
            name=d["name"], uid=d["uid"], ftype_name=d["typeName"],
            is_response=d["isResponse"], is_raw=d["isRaw"],
            origin_stage_uid=d["originStage"],
            parent_uids=tuple(d.get("parents", ())),
        )

    def __repr__(self) -> str:
        return f"TransientFeature({self.name!r}, {self.ftype_name})"
