"""Typed feature construction.

Parity: reference ``features/FeatureBuilder.scala:48-351`` — one typed factory
per feature type (``FeatureBuilder.Real[Passenger]("age").extract(...)
.asPredictor``) plus schema-driven construction from a data frame
(``fromDataFrame``). The Scala macro that captures extract-fn source for
serialization maps to requiring importable (module-level) extract functions.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

from transmogrifai_tpu.features.feature import Feature
from transmogrifai_tpu.frame import HostFrame
from transmogrifai_tpu.stages.base import FeatureGeneratorStage
from transmogrifai_tpu.types import feature_types as ft

__all__ = ["FeatureBuilder"]


class TypedFeatureBuilder:
    """Builder for one raw feature of a fixed type."""

    def __init__(self, name: str, ftype: type[ft.FeatureType]):
        self._name = name
        self._ftype = ftype
        self._extract_fn: Optional[Callable[[Any], Any]] = None
        self._aggregator = None
        self._window = None

    def extract(self, fn: Callable[[Any], Any]) -> "TypedFeatureBuilder":
        """Record -> python value extractor (None = missing)."""
        self._extract_fn = fn
        return self

    def aggregate(self, aggregator) -> "TypedFeatureBuilder":
        """Override the default monoid aggregator for event rollup."""
        self._aggregator = aggregator
        return self

    def window(self, window_ms: int) -> "TypedFeatureBuilder":
        """Time window (ms before cutoff) for event aggregation."""
        self._window = window_ms
        return self

    def source(self, tag: str) -> "TypedFeatureBuilder":
        """Bind this feature to the reader carrying the same source tag
        (reference: features bind to a reader via FeatureBuilder's record
        TYPE parameter; joined readers route extracted features by it —
        here the binding is an explicit tag, see
        DataReader.with_source_tag)."""
        self._source_tag = tag
        return self

    def _build(self, is_response: bool) -> Feature:
        stage = FeatureGeneratorStage(
            name=self._name, ftype_name=self._ftype.__name__,
            extract_fn=self._extract_fn, aggregator=self._aggregator,
            is_response=is_response)
        stage.window_ms = self._window
        stage.source_tag = getattr(self, "_source_tag", None)
        return stage.get_output()

    def as_predictor(self) -> Feature:
        return self._build(is_response=False)

    def as_response(self) -> Feature:
        return self._build(is_response=True)

    # camelCase aliases matching the reference API surface
    asPredictor = as_predictor
    asResponse = as_response


class _FeatureBuilderMeta(type):
    def __getattr__(cls, type_name: str):
        try:
            ftype = ft.feature_type_of(type_name)
        except KeyError:
            raise AttributeError(
                f"FeatureBuilder.{type_name}: not a feature type") from None

        def make(name: str) -> TypedFeatureBuilder:
            return TypedFeatureBuilder(name, ftype)

        return make


class FeatureBuilder(metaclass=_FeatureBuilderMeta):
    """``FeatureBuilder.Real("age").extract(fn).as_predictor()`` etc., one
    factory per registered feature type, plus frame-driven construction."""

    @staticmethod
    def from_frame(frame: HostFrame, response: Optional[str] = None
                   ) -> dict[str, Feature]:
        """Build raw features straight from a HostFrame's schema (the analog
        of FeatureBuilder.fromDataFrame). The response column, if named, is
        marked as response."""
        out: dict[str, Feature] = {}
        for name, col in frame.columns.items():
            stage = FeatureGeneratorStage(
                name=name, ftype_name=col.ftype.__name__,
                is_response=(name == response))
            out[name] = stage.get_output()
        if response is not None and response not in out:
            raise KeyError(f"Response column {response!r} not in frame")
        return out
