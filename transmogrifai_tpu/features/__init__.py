from transmogrifai_tpu.features.feature import Feature, FeatureLike, TransientFeature

__all__ = ["Feature", "FeatureLike", "TransientFeature", "FeatureBuilder"]


def __getattr__(name):
    # FeatureBuilder imports stages.base (which itself imports this package's
    # feature module); resolve it lazily to keep the import graph acyclic.
    if name == "FeatureBuilder":
        from transmogrifai_tpu.features.builder import FeatureBuilder
        return FeatureBuilder
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
