from transmogrifai_tpu.features.feature import Feature, FeatureLike, TransientFeature
from transmogrifai_tpu.features.builder import FeatureBuilder

__all__ = ["Feature", "FeatureLike", "TransientFeature", "FeatureBuilder"]
