"""Resumable-training checkpoints: per-layer fitted-DAG persistence.

The reference inherits Spark lineage recovery — a lost executor refits
nothing because fitted stages live on the driver. Our analog of a lost
executor is a preempted TPU job: the whole process dies, and before this
module every fitted stage outside the ModelSelector's ``sweep.json`` died
with it. ``Workflow.train(checkpoint_dir=...)`` now persists each fitted
DAG layer as it completes — the same (json record, npz arrays) unit
``serialization.save_model`` writes, plus the output-feature uid used to
graft restored stages back onto a rebuilt workflow via the
``_substitute_fitted`` replay seam. A restarted ``train`` replays completed
layers from disk (no refit), composes with the sweep checkpoint (a mid-CV
crash resumes both the before-DAG and the partially-done sweep), and
counts ``layers_resumed``/``stages_resumed`` in ``utils.profiling.
run_counters``.

Durability contract:

- every write is atomic (tmp + ``os.replace``): a crash mid-write leaves
  the previous manifest intact, never a truncated one;
- the manifest carries a fingerprint of the DAG structure + data shape; a
  checkpoint from a different workflow/data is ignored with a warning
  (fresh start), as is a corrupted or truncated file — stale state can
  cost a refit, never correctness;
- saving is best-effort: a checkpoint-write failure (injectable at fault
  site ``checkpoint.write``) warns and training continues — only simulated
  preemption propagates.

Layout: ``<dir>/train_manifest.json`` + ``<dir>/layer_<key>.npz`` (one
per layer, keyed by the layer's stable identity hash; plus the
ModelSelector's ``<dir>/sweep.json`` when training composes the two).
"""

from __future__ import annotations

import hashlib
import json
import os
import warnings
from typing import Optional

import numpy as np

from transmogrifai_tpu.serialization import (
    fitted_stage_record, restore_fitted_stage,
)
from transmogrifai_tpu.stages.base import Estimator, PipelineStage
from transmogrifai_tpu.types import feature_types as ft
from transmogrifai_tpu.utils.durable import ensure_checkpoint_dir

__all__ = ["TrainCheckpoint", "train_fingerprint", "model_fingerprint",
           "TRAIN_MANIFEST"]

TRAIN_MANIFEST = "train_manifest.json"
FORMAT_VERSION = 1


def model_fingerprint(model=None, path: Optional[str] = None) -> str:
    """Identity of a FITTED model — the serving fleet's registry key and
    the shared compiled-program cache's jit-key prefix.

    Two models with identical DAG structure but different fitted state
    (different training data, a retrained version) MUST fingerprint
    differently: a compiled-program cache entry traced from one model's
    parameters is only reusable by a model whose parameter pytree is
    byte-identical. So, unlike :func:`train_fingerprint` (which matches a
    RUN for resume and deliberately excludes fitted state), this hashes
    the full persisted form.

    ``path`` (a ``serialization.save_model`` directory) hashes the saved
    manifest + array bytes — deterministic across processes, so every
    load of the same checkpoint dir shares compiled entries. ``model``
    (in-memory, never saved) hashes the same ``fitted_stage_record``
    units the writer would produce. The two derivations are NOT
    comparable with each other — a registry keys every dir-loaded model
    by its path hash.
    """
    h = hashlib.sha256()
    if path is not None:
        from transmogrifai_tpu.serialization import ARRAYS_NPZ, MODEL_JSON
        found = False
        for name in (MODEL_JSON, ARRAYS_NPZ):
            p = os.path.join(path, name)
            if not os.path.exists(p):
                continue
            found = True
            h.update(name.encode())
            with open(p, "rb") as fh:
                for chunk in iter(lambda: fh.read(1 << 20), b""):
                    h.update(chunk)
        if not found:
            raise FileNotFoundError(
                f"no saved model (model.json) under {path!r}")
        return h.hexdigest()[:16]
    if model is None:
        raise ValueError("model_fingerprint needs a model or a path")
    for layer in model.dag:
        for t in layer:
            rec, arrays = fitted_stage_record(t)
            h.update(json.dumps(rec, sort_keys=True,
                                default=str).encode())
            for k in sorted(arrays):
                h.update(k.encode())
                h.update(np.ascontiguousarray(arrays[k]).tobytes())
    h.update(json.dumps(
        [[f.name, f.ftype.__name__] for f in model.raw_features]
        + [[f.name, f.ftype.__name__] for f in model.result_features],
        sort_keys=True).encode())
    return h.hexdigest()[:16]


def train_fingerprint(dag, n_rows: int, raw_names) -> str:
    """Identity of a training run for resume matching: the leveled DAG
    structure (stage classes, uids, wiring) plus the data's coarse shape.
    Deliberately EXCLUDES stage configs — they can hold live objects whose
    reprs differ across processes — and deliberately cheap: it must not
    scan the data. Same-shaped different data cannot be distinguished from
    a restart; point each dataset at its own checkpoint directory."""
    spec = {
        "nRows": int(n_rows),
        "raw": sorted(raw_names),
        "layers": [[[type(s).__name__, s.uid, s.operation_name,
                     s.get_output().uid,
                     [f.uid for f in s.input_features]]
                    for s in layer] for layer in dag],
    }
    return hashlib.sha256(
        json.dumps(spec, sort_keys=True).encode()).hexdigest()[:16]


class TrainCheckpoint:
    """Fingerprinted, atomically-written per-layer training checkpoint."""

    def __init__(self, path: str, fingerprint: str):
        self.path = path
        self.fingerprint = fingerprint
        #: stable layer key -> {"index": display index, "stages": records}.
        #: Keyed by layer IDENTITY (hash of the member stages' output
        #: feature uids), NOT by position: the workflow-CV path
        #: (before/during/tail) and the plain path level the same stages
        #: into different positional indices, and a resume that switches
        #: paths must never overwrite one layer's entry with another's
        self._layers: dict[str, dict] = {}
        #: unusable directory (read-only mount, permissions): training
        #: proceeds un-checkpointed — same best-effort contract as writes
        self._disabled = not ensure_checkpoint_dir(path, "train checkpoint")
        if not self._disabled:
            self._load()

    # -- manifest io ---------------------------------------------------------
    def _manifest_path(self) -> str:
        return os.path.join(self.path, TRAIN_MANIFEST)

    def _arrays_path(self, key: str) -> str:
        return os.path.join(self.path, f"layer_{key}.npz")

    @staticmethod
    def _layer_key(fitted_layer) -> str:
        """Stable identity of a layer: its member stages' output features
        (shared between estimator and fitted model, deterministic across
        resume runs — unlike fitted-model uids, which are minted at fit
        time)."""
        uids = "|".join(sorted(t.get_output().uid for t in fitted_layer))
        return hashlib.sha256(uids.encode()).hexdigest()[:12]

    def _load(self) -> None:
        path = self._manifest_path()
        if not os.path.exists(path):
            return
        try:
            with open(path) as fh:
                manifest = json.load(fh)
            if manifest.get("formatVersion") != FORMAT_VERSION:
                raise ValueError(
                    f"format {manifest.get('formatVersion')!r} != "
                    f"{FORMAT_VERSION}")
            layers = {str(k): {"index": v.get("index", -1),
                               "stages": list(v.get("stages", []))}
                      for k, v in manifest.get("layers", {}).items()}
        except Exception as e:  # noqa: BLE001 — corrupt checkpoint != crash
            warnings.warn(
                f"train checkpoint: unreadable manifest at {path!r} "
                f"({type(e).__name__}: {e}); starting fresh", RuntimeWarning)
            return
        if manifest.get("fingerprint") != self.fingerprint:
            warnings.warn(
                f"train checkpoint at {path!r} was written by a different "
                "workflow/data (fingerprint mismatch); starting fresh",
                RuntimeWarning)
            return
        self._layers = layers

    @property
    def n_layers_done(self) -> int:
        return len(self._layers)

    # -- restore -------------------------------------------------------------
    def restore_overrides(self, dag) -> dict[str, PipelineStage]:
        """Rebuild fitted transformers for every checkpointed stage that
        matches an ESTIMATOR position in the current (pre-substitution)
        ``dag``, wired to the live feature graph. Returns
        ``{output_feature_uid: fitted transformer}`` for
        ``Workflow._substitute_fitted``. Non-estimator matches are skipped
        (the live transformer is already usable); unmatched or unrestorable
        records are skipped with a warning — they cost a refit, not a
        crash."""
        from transmogrifai_tpu.utils.devicewatch import guard
        from transmogrifai_tpu.utils.profiling import run_counters
        from transmogrifai_tpu.utils.tracing import span
        if not self._layers:
            return {}
        with span("checkpoint.restore", n_layers=len(self._layers)), \
                guard("checkpoint.restore", site="checkpoint.restore",
                      nLayers=len(self._layers)):
            return self._restore_overrides(dag, run_counters)

    def _restore_overrides(self, dag, run_counters
                           ) -> dict[str, PipelineStage]:
        current = {s.get_output().uid: s for layer in dag for s in layer}
        overrides: dict[str, PipelineStage] = {}
        for key in sorted(self._layers):
            arrays: dict = {}
            apath = self._arrays_path(key)
            if os.path.exists(apath):
                try:
                    arrays = dict(np.load(apath, allow_pickle=False))
                except Exception as e:  # noqa: BLE001 — refit, don't crash
                    warnings.warn(
                        f"train checkpoint: unreadable arrays {apath!r} "
                        f"({type(e).__name__}: {e}); refitting that layer",
                        RuntimeWarning)
                    continue
            for rec in self._layers[key]["stages"]:
                out_uid = rec.get("outputFeatureUid")
                cur = current.get(out_uid)
                if cur is None:
                    warnings.warn(
                        "train checkpoint: stage "
                        f"{rec.get('uid')!r} has no match in the current "
                        "DAG; ignoring its checkpoint entry", RuntimeWarning)
                    continue
                if not isinstance(cur, Estimator):
                    continue  # live transformer already usable as-is
                try:
                    stage = restore_fitted_stage(rec, arrays)
                except Exception as e:  # noqa: BLE001 — refit, don't crash
                    warnings.warn(
                        f"train checkpoint: cannot restore stage "
                        f"{rec.get('uid')!r} ({type(e).__name__}: {e}); "
                        "it will be refit", RuntimeWarning)
                    continue
                stage._inputs = cur.input_features
                stage._output = cur.get_output()
                # type-preserving stages resolve out_type at set_input
                # time, which grafting bypasses (same fix as load_model)
                if type(stage).out_type in (ft.FeatureType, ft.OPMap,
                                            ft.OPCollection):
                    stage.out_type = stage._output.ftype
                stage._from_checkpoint = True
                overrides[out_uid] = stage
                run_counters.stages_resumed += 1
        return overrides

    # -- save ----------------------------------------------------------------
    def save_layer(self, li: int, fitted_layer) -> None:
        """Persist one completed layer's fitted stages (atomic +
        best-effort via ``utils.durable``: a write failure warns and
        training continues). Stages that cannot serialize are skipped
        individually with a warning — the rest of the layer still
        checkpoints and only the skipped stage refits on resume."""
        from transmogrifai_tpu.utils.durable import (
            atomic_json_dump, best_effort_checkpoint_write,
        )
        from transmogrifai_tpu.utils.tracing import span
        if self._disabled:
            return
        with span("checkpoint.save_layer", layer=li,
                  n_stages=len(fitted_layer)):
            self._save_layer(li, fitted_layer, atomic_json_dump,
                             best_effort_checkpoint_write)

    def _save_layer(self, li: int, fitted_layer, atomic_json_dump,
                    best_effort_checkpoint_write) -> None:
        recs: list[dict] = []
        arrays: dict[str, np.ndarray] = {}
        for t in fitted_layer:
            try:
                rec, t_arrays = fitted_stage_record(t)
            except Exception as e:  # noqa: BLE001 — best-effort per stage
                warnings.warn(
                    f"train checkpoint: stage {t.uid} does not serialize "
                    f"({type(e).__name__}: {e}); it will refit on resume",
                    RuntimeWarning)
                continue
            rec["outputFeatureUid"] = t.get_output().uid
            recs.append(rec)
            arrays.update(t_arrays)

        key = self._layer_key(fitted_layer)

        def write() -> None:
            if arrays:
                atmp = self._arrays_path(key) + ".tmp.npz"
                with open(atmp, "wb") as fh:
                    np.savez(fh, **arrays)
                os.replace(atmp, self._arrays_path(key))
            self._layers[key] = {"index": li, "stages": recs}
            manifest = {
                "formatVersion": FORMAT_VERSION,
                "fingerprint": self.fingerprint,
                "layers": {k: v for k, v in sorted(self._layers.items())},
            }
            atomic_json_dump(manifest, self._manifest_path(), indent=1,
                             default=_np_default)

        if not best_effort_checkpoint_write(
                write, f"train checkpoint: write for layer {li} failed; "
                       "training continues without it"):
            self._layers.pop(key, None)


def _np_default(o):
    if isinstance(o, np.integer):
        return int(o)
    if isinstance(o, np.floating):
        return float(o)
    if isinstance(o, np.ndarray):
        return o.tolist()
    raise TypeError(f"Not JSON serializable: {type(o)}")
