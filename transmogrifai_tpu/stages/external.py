"""Generic wrappers for external estimators/transformers.

Parity: reference ``core/.../stages/sparkwrappers/generic/Sw*.scala`` (12
files) + ``SparkWrapperParams`` — wrap *any* third-party Transformer or
Estimator as a pipeline stage. The Spark version wraps JVM stages and ships
them via MLeap; the TPU-native equivalent wraps plain Python callables:

- ``ExternalEstimatorWrapper``: ``fit_fn(X, y, w) -> state`` plus
  ``predict_fn(state, X) -> scores`` (numpy in/out; e.g. an sklearn-style
  library or hand-rolled numpy model). Runs on host — external engines
  don't trace under jit — while everything upstream stays fused on device.
- ``ExternalTransformerWrapper``: ``transform_fn(X) -> X2`` over the
  feature-vector block.

Both serialize like LambdaTransformer: the callables must be importable
module-level functions, and the fitted state must be a dict of numpy
arrays/JSON-able values (the same contract as ``fitted_state``).
"""

from __future__ import annotations

import importlib
from typing import Any, Callable, Optional

import numpy as np

from transmogrifai_tpu import frame as fr
from transmogrifai_tpu.stages.base import Estimator, HostTransformer
from transmogrifai_tpu.types import feature_types as ft
from transmogrifai_tpu.vector_metadata import (
    VectorColumnMetadata, VectorMetadata, parent_of,
)

__all__ = ["ExternalEstimatorWrapper", "ExternalPredictionModel",
           "ExternalTransformerWrapper"]


def _fn_path(fn: Callable) -> str:
    mod = getattr(fn, "__module__", None)
    qn = getattr(fn, "__qualname__", "")
    if not mod or "<lambda>" in qn or "<locals>" in qn:
        raise ValueError(
            f"External wrapper function {fn!r} must be an importable "
            "module-level function to be serializable")
    return f"{mod}:{qn}"


def _fn_from_path(path: str) -> Callable:
    mod, _, qn = path.partition(":")
    obj: Any = importlib.import_module(mod)
    for part in qn.split("."):
        obj = getattr(obj, part)
    return obj


class ExternalEstimatorWrapper(Estimator):
    """(label RealNN, features OPVector) -> Prediction via external fns.

    ``fit_fn(X, y, w) -> state``; ``predict_fn(state, X) -> scores`` where
    scores is [n] (binary margin / regression value) or [n, C] class
    probabilities.
    """

    in_types = (ft.RealNN, ft.OPVector)
    out_type = ft.Prediction

    def __init__(self, fit_fn: Callable | str, predict_fn: Callable | str,
                 uid: Optional[str] = None):
        self.fit_fn = _fn_from_path(fit_fn) if isinstance(fit_fn, str) \
            else fit_fn
        self.predict_fn = _fn_from_path(predict_fn) \
            if isinstance(predict_fn, str) else predict_fn
        super().__init__(uid=uid)

    def fit_model(self, data):
        label_name, feat_name = self.input_names
        y = np.asarray(data.device_col(label_name).values, np.float64)
        X = np.asarray(data.device_col(feat_name).values, np.float64)
        w = np.ones_like(y)
        state = self.fit_fn(X, y, w)
        if not isinstance(state, dict):
            raise TypeError(
                f"fit_fn must return a dict state, got {type(state)}")
        return ExternalPredictionModel(
            predict_fn=self.predict_fn, state=state)

    def config(self):
        return {"fit_fn": _fn_path(self.fit_fn),
                "predict_fn": _fn_path(self.predict_fn)}


class ExternalPredictionModel(HostTransformer):
    in_types = (ft.RealNN, ft.OPVector)
    out_type = ft.Prediction

    def __init__(self, predict_fn: Callable | str,
                 state: Optional[dict] = None, uid: Optional[str] = None):
        self.predict_fn = _fn_from_path(predict_fn) \
            if isinstance(predict_fn, str) else predict_fn
        self.state = state or {}
        super().__init__(uid=uid)

    def runtime_input_names(self):
        return (self.input_names[-1],)

    def _scores_to_prediction(self, scores: np.ndarray) -> list[dict]:
        scores = np.asarray(scores, np.float64)
        out = []
        if scores.ndim == 1:
            # binary margin or regression value: mirror PredictionColumn's
            # single-score contract
            for s in scores:
                out.append({"prediction": float(s)})
        else:
            for row in scores:
                k = int(np.argmax(row))
                d = {"prediction": float(k)}
                for j, p in enumerate(row):
                    d[f"rawPrediction_{j}"] = float(p)
                    d[f"probability_{j}"] = float(p)
                out.append(d)
        return out

    def transform_row(self, *values):
        X = np.asarray(values[-1], np.float64)[None, :]
        return self._scores_to_prediction(
            self.predict_fn(self.state, X))[0]

    def host_apply(self, *cols):
        X = np.asarray(cols[-1].values, np.float64)
        preds = self._scores_to_prediction(self.predict_fn(self.state, X))
        return fr.HostColumn.from_values(ft.Prediction, preds)

    def output_column(self, data):
        return self.host_apply(*[data.host_col(n)
                                 for n in self.runtime_input_names()])

    def fitted_state(self):
        return dict(self.state)

    def set_fitted_state(self, state):
        self.state = dict(state)

    def config(self):
        return {"predict_fn": _fn_path(self.predict_fn)}


class ExternalTransformerWrapper(HostTransformer):
    """OPVector -> OPVector through an arbitrary numpy function."""

    in_types = (ft.OPVector,)
    out_type = ft.OPVector

    def __init__(self, transform_fn: Callable | str,
                 uid: Optional[str] = None):
        self.transform_fn = _fn_from_path(transform_fn) \
            if isinstance(transform_fn, str) else transform_fn
        super().__init__(uid=uid)

    def transform_row(self, value):
        return np.asarray(
            self.transform_fn(np.asarray(value)[None, :])[0], np.float32)

    def host_apply(self, *cols):
        X = np.asarray(cols[0].values)
        X2 = np.asarray(self.transform_fn(X), np.float32)
        name = self.get_output().name
        f = self.input_features[0]
        meta = VectorMetadata(name, tuple(
            VectorColumnMetadata(*parent_of(f), grouping=f.name,
                                 descriptor_value=f"external_{j}")
            for j in range(X2.shape[1]))).reindexed(0)
        return fr.HostColumn(ft.OPVector, X2, meta=meta)

    def config(self):
        return {"transform_fn": _fn_path(self.transform_fn)}
