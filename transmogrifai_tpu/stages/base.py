"""Typed pipeline stages over features (not columns).

Parity: reference ``features/src/main/scala/com/salesforce/op/stages/
OpPipelineStages.scala:55-552`` and ``stages/base/*`` — stages declare typed
feature inputs/outputs, validate input types, and produce output features
lazily; ``OpTransformer`` adds the row-level path used for local scoring.

TPU-first divergence: instead of the reference's per-row UDF closures, a
transformer here exposes up to three execution paths:

- **device path** (``DeviceTransformer.device_apply``): a pure jittable
  function of (params pytree, device columns) -> device column. All device
  transformers of one DAG layer are fused into a single jitted program by the
  executor (the analog of ``FitStagesUtil.applyOpTransformations`` fusing all
  row closures of a layer into one RDD pass).
- **host path** (``HostTransformer.host_apply``): eager numpy/python over
  host columns — for string-shaped work that stays off the device.
- **row path** (``transform_row``): plain-python single-record scoring; the
  contract tests assert row path == columnar path (the reference's
  OpTransformerSpec invariant).

Estimators fit on the pipeline data and return a fitted Transformer (model).
"""

from __future__ import annotations

import inspect
from typing import Any, Callable, Optional, Sequence

import numpy as np

from transmogrifai_tpu.features.feature import Feature, FeatureLike
from transmogrifai_tpu.frame import HostColumn
from transmogrifai_tpu.types import feature_types as ft
from transmogrifai_tpu.uid import UID

__all__ = [
    "PipelineStage", "Transformer", "HostTransformer", "DeviceTransformer",
    "Estimator", "LambdaTransformer", "FeatureGeneratorStage",
    "STAGE_REGISTRY", "AllowLabelAsInput",
]

#: class-name -> stage class, for model deserialization (the analog of the
#: reference's reflection-based stage reader)
STAGE_REGISTRY: dict[str, type["PipelineStage"]] = {}


class AllowLabelAsInput:
    """Marker: stage may legitimately consume the response feature."""


class PipelineStage:
    """Base of all stages.

    Subclasses declare:
      - ``in_types``: tuple of FeatureType classes, one per input; for
        variadic (sequence) stages set ``variadic = True`` and give the
        element type as the last entry (preceding entries are fixed inputs).
      - ``out_type``: output FeatureType class.
    """

    in_types: tuple[type[ft.FeatureType], ...] = ()
    out_type: type[ft.FeatureType] = ft.FeatureType
    variadic: bool = False
    is_raw_generator: bool = False

    def __init_subclass__(cls, **kw):
        super().__init_subclass__(**kw)
        STAGE_REGISTRY[cls.__name__] = cls

    def __init__(self, operation_name: Optional[str] = None,
                 uid: Optional[str] = None):
        self.uid = uid or UID.of(type(self))
        self.operation_name = operation_name or type(self).__name__
        self._inputs: tuple[FeatureLike, ...] = ()
        self._output: Optional[Feature] = None

    # -- input/output wiring -------------------------------------------------
    def set_input(self, *features: FeatureLike) -> "PipelineStage":
        self.validate_inputs(features)
        self._inputs = tuple(features)
        self._output = None
        return self

    def validate_inputs(self, features: Sequence[FeatureLike]) -> None:
        if self.variadic:
            n_fixed = len(self.in_types) - 1
            if len(features) < n_fixed + 1:
                raise ValueError(
                    f"{self}: needs at least {n_fixed + 1} inputs, got {len(features)}")
            expected = list(self.in_types[:n_fixed]) + [self.in_types[-1]] * (
                len(features) - n_fixed)
        else:
            if len(features) != len(self.in_types):
                raise ValueError(
                    f"{self}: expects {len(self.in_types)} inputs, got {len(features)}")
            expected = list(self.in_types)
        for f, t in zip(features, expected):
            if not ft.is_subtype(f.ftype, t):
                raise TypeError(
                    f"{self}: input {f.name!r} has type {f.ftype.__name__}, "
                    f"expected {t.__name__}")
        labelish = [f for f in features if f.is_response]
        if labelish and not isinstance(self, (AllowLabelAsInput, Estimator)):
            raise ValueError(
                f"{self}: response feature(s) {[f.name for f in labelish]} "
                "cannot feed a plain transformer (label leakage)")

    @property
    def input_features(self) -> tuple[FeatureLike, ...]:
        return self._inputs

    @property
    def input_names(self) -> tuple[str, ...]:
        return tuple(f.name for f in self._inputs)

    def make_output_name(self) -> str:
        base = "-".join(f.name for f in self._inputs[:3]) or "root"
        _, n = UID.from_string(self.uid)
        return f"{base}_{len(self._inputs)}-stagesApplied_{self.operation_name}_{n:012d}"

    def output_is_response(self) -> bool:
        """Derived features stay responses only when every input is one
        (e.g. an indexed label); any predictor input makes the output a
        predictor. This is what workflow-level CV's label-dependence cut
        keys off, so response-ness must survive label derivations."""
        return bool(self._inputs) and all(f.is_response for f in self._inputs)

    def get_output(self) -> Feature:
        if not self._inputs and not self.is_raw_generator:
            raise ValueError(f"{self}: set_input before get_output")
        if self._output is None:
            self._output = Feature(
                name=self.make_output_name(), uid=UID.of("Feature"),
                ftype=self.out_type, origin_stage=self, parents=self._inputs,
                is_response=self.output_is_response(),
            )
        return self._output

    # -- serialization -------------------------------------------------------
    def config(self) -> dict:
        """JSON-able constructor arguments. Default: reflect the __init__
        signature and read identically-named attributes (the analog of the
        reference's ctor-reflection DefaultOpPipelineStageReaderWriter)."""
        sig = inspect.signature(type(self).__init__)
        out = {}
        for name, p in sig.parameters.items():
            if name in ("self", "uid") or p.kind in (p.VAR_POSITIONAL, p.VAR_KEYWORD):
                continue
            missing = object()
            v = getattr(self, name, missing)
            if v is missing:
                v = getattr(self, "_" + name, missing)
            if v is missing:
                raise NotImplementedError(
                    f"{type(self).__name__}.config(): cannot reflect ctor arg "
                    f"{name!r}; override config()")
            out[name] = v
        return out

    @classmethod
    def from_config(cls, config: dict, uid: Optional[str] = None) -> "PipelineStage":
        return cls(uid=uid, **config)

    def fitted_state(self) -> dict[str, Any]:
        """Arrays/values learned at fit time (empty for pure transformers)."""
        return {}

    def set_fitted_state(self, state: dict[str, Any]) -> None:
        if state:
            raise NotImplementedError(
                f"{type(self).__name__} got fitted state but defines none")

    def __repr__(self) -> str:
        return f"{type(self).__name__}({self.uid})"


# ---------------------------------------------------------------------------
# Transformers
# ---------------------------------------------------------------------------

class Transformer(PipelineStage):
    """A fitted/stateless stage: maps input columns to an output column."""

    is_device: bool = False

    def runtime_input_names(self) -> tuple[str, ...]:
        """Inputs actually required at transform time. Prediction models
        declare (label, features) but consume only features, so scoring
        works on label-less data (reference SelectedModel.transformFn)."""
        return self.input_names

    def transform_row(self, *values: Any) -> Any:
        """Single-record scoring on plain python values (None = missing)."""
        raise NotImplementedError

    def output_column(self, data: "Any") -> Any:  # -> HostColumn | DeviceColumn
        """Columnar transform against a PipelineData; dispatched by executor."""
        raise NotImplementedError


class HostTransformer(Transformer):
    """Eager numpy/python columnar transformer (string-shaped work)."""

    def host_apply(self, *cols: HostColumn) -> HostColumn:
        """Default: row-loop over transform_row (override to vectorize)."""
        n = len(cols[0]) if cols else 0
        vals = [self.transform_row(*(c.python_value(i) for c in cols))
                for i in range(n)]
        return HostColumn.from_values(self.out_type, vals)

    def output_column(self, data) -> HostColumn:
        cols = [data.host_col(n) for n in self.runtime_input_names()]
        return self.host_apply(*cols)


class DeviceTransformer(Transformer):
    """Jittable columnar transformer, fused per DAG layer by the executor.

    ``device_apply(params, *cols)`` must be pure in its arguments: all fitted
    state rides in the params pytree; static config (widths, flags) may be
    read from ``self`` (it is closed over at trace time and must be
    trace-stable).
    """

    is_device = True

    def device_params(self) -> Any:
        return ()

    def quantize_device_params(self, precision: str) -> Any:
        """Precision-ladder hook: return a params pytree specialized for a
        non-f32 rung, or ``None`` to use ``device_params()`` with the
        builder's generic float cast. Stages with quantizable weight
        payloads (linear/GLM/MLP/NB matmul weights, tree index/threshold
        arrays) override this; returned trees may carry
        ``QuantizedTensor``/``ExactTensor`` leaves which the fused program
        materializes in-trace, so ``device_apply`` stays unchanged."""
        return None

    def device_apply(self, params: Any, *cols: Any) -> Any:
        raise NotImplementedError

    def output_column(self, data) -> Any:
        cols = [data.device_col(n) for n in self.runtime_input_names()]
        return self.device_apply(self.device_params(), *cols)


class LambdaTransformer(HostTransformer):
    """Arbitrary-arity row-function transformer — the analog of the reference
    ``Unary/Binary/Ternary/Quaternary/SequenceTransformer`` lambda bases.

    The lambda operates on plain python values. Not serializable unless the
    function is importable (module-level), mirroring the reference's
    requirement that lambdas be stable classes for serialization.
    """

    def __init__(self, fn: Callable, in_types: tuple, out_type: type,
                 operation_name: Optional[str] = None, variadic: bool = False,
                 uid: Optional[str] = None):
        self.in_types = tuple(in_types)
        self.out_type = out_type
        self.variadic = variadic
        self.fn = fn
        super().__init__(operation_name=operation_name or getattr(
            fn, "__name__", "lambda"), uid=uid)

    def transform_row(self, *values):
        return self.fn(*values)

    def config(self) -> dict:
        fn = self.fn
        mod, qn = getattr(fn, "__module__", None), getattr(fn, "__qualname__", "")
        if not mod or "<lambda>" in qn or "<locals>" in qn:
            raise NotImplementedError(
                "LambdaTransformer with a non-importable function cannot be "
                "serialized; define the function at module level")
        return {
            "fn": f"{mod}:{qn}",
            "in_types": [t.__name__ for t in self.in_types],
            "out_type": self.out_type.__name__,
            "operation_name": self.operation_name,
            "variadic": self.variadic,
        }

    @classmethod
    def from_config(cls, config: dict, uid: Optional[str] = None):
        import importlib
        mod, _, qn = config["fn"].partition(":")
        obj: Any = importlib.import_module(mod)
        for part in qn.split("."):
            obj = getattr(obj, part)
        return cls(
            fn=obj,
            in_types=tuple(ft.feature_type_of(t) for t in config["in_types"]),
            out_type=ft.feature_type_of(config["out_type"]),
            operation_name=config["operation_name"],
            variadic=config["variadic"], uid=uid,
        )


# ---------------------------------------------------------------------------
# Estimators
# ---------------------------------------------------------------------------

class Estimator(PipelineStage):
    """A stage that learns state from data and yields a fitted Transformer.

    Parity: reference ``UnaryEstimator.fit`` etc. — ``fit`` sees the pipeline
    data (host + device views) and must return a Transformer wired to the
    same inputs/uid-derived output so DAG identity is preserved.
    """

    def fit(self, data: "Any") -> Transformer:
        model = self.fit_model(data)
        model._inputs = self._inputs
        model._output = self._output  # share the output feature node
        if model._output is None:
            # materialize output feature from the estimator so downstream
            # features built pre-fit keep pointing at the right node
            model._output = self.get_output()
        return model

    def fit_model(self, data: "Any") -> Transformer:
        raise NotImplementedError


# ---------------------------------------------------------------------------
# Raw feature origin
# ---------------------------------------------------------------------------

class FeatureGeneratorStage(PipelineStage):
    """Stage 0 of every DAG: extracts a raw feature from an input record.

    Parity: reference ``stages/FeatureGeneratorStage.scala:66-120`` —
    ``extract_fn: record -> python value`` plus an optional monoid aggregator
    and time window for event-level -> entity-level rollup (executed by the
    readers, not the DAG executor).
    """

    is_raw_generator = True

    def __init__(self, name: str, ftype_name: str,
                 extract_fn: Optional[Callable[[Any], Any]] = None,
                 aggregator: Optional[Any] = None,
                 is_response: bool = False,
                 uid: Optional[str] = None):
        super().__init__(operation_name=f"raw_{name}", uid=uid)
        self.name = name
        self.ftype_name = ftype_name
        self.extract_fn = extract_fn
        self.aggregator = aggregator
        self.is_response = is_response
        self.out_type = ft.feature_type_of(ftype_name)

    def extract(self, record: Any) -> Any:
        if self.extract_fn is not None:
            return self.extract_fn(record)
        if isinstance(record, dict):
            return record.get(self.name)
        return getattr(record, self.name)

    def output_is_response(self) -> bool:
        return self.is_response

    def make_output_name(self) -> str:
        return self.name

    def get_output(self) -> Feature:
        if self._output is None:
            self._output = Feature(
                name=self.name, uid=UID.of("Feature"), ftype=self.out_type,
                origin_stage=self, parents=(), is_response=self.is_response)
        return self._output

    def config(self) -> dict:
        return {
            "name": self.name, "ftype_name": self.ftype_name,
            "is_response": self.is_response,
        }
