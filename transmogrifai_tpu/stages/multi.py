"""Multi-output (arity-typed) stage surface.

Parity: reference ``features/.../stages/OpPipelineStages.scala:240-455`` —
the ``OpPipelineStage1to2 / 1to3 / 2to2 / 2to3 / 3to2`` traits that let one
stage emit several typed features. (The reference defines this surface
without shipping concrete implementations; users extend it. Same here.)

Design: the executor's DAG contract stays one-column-per-stage, so a
multi-output stage never enters the DAG itself — ``get_outputs()`` wires M
lightweight VIEW stages over the same inputs, each owning one output
feature. The parent computes the full output tuple ONCE per batch (memoized
on the data object) and views select their slot; on the local row path each
view replays ``transform_row_multi`` and picks its element.
"""

from __future__ import annotations

import weakref
from typing import Any, Optional

from transmogrifai_tpu.frame import HostColumn
from transmogrifai_tpu.stages.base import HostTransformer, STAGE_REGISTRY

__all__ = ["MultiOutputHostTransformer"]

#: deserialized views of one parent share a single instance (and thus the
#: batch memo) — keyed by the saved parent uid, weakly so nothing leaks
_PARENT_CACHE: "weakref.WeakValueDictionary[str, MultiOutputHostTransformer]" \
    = weakref.WeakValueDictionary()


class MultiOutputHostTransformer(HostTransformer):
    """Base for N-in / M-out host transformers.

    Subclasses declare ``in_types`` (as usual) plus ``out_types`` (one per
    output) and implement ``transform_row_multi(*values) -> tuple``. Use
    ``get_outputs()`` (not ``get_output()``) to obtain the M features.
    """

    out_types: tuple[type, ...] = ()

    def __init__(self, operation_name: Optional[str] = None,
                 uid: Optional[str] = None):
        super().__init__(operation_name=operation_name, uid=uid)
        self._views: Optional[tuple] = None
        #: (weakref to the data object, columns tuple) — a weak reference
        #: cannot alias a NEW object at a recycled address (id() could)
        self._batch_memo: Optional[tuple] = None

    # -- to implement --------------------------------------------------------
    def transform_row_multi(self, *values: Any) -> tuple:
        raise NotImplementedError

    def host_apply_multi(self, *cols: HostColumn) -> tuple[HostColumn, ...]:
        """Default: row-loop over transform_row_multi (override to
        vectorize)."""
        n = len(cols[0]) if cols else 0
        rows = [self.transform_row_multi(
            *(c.python_value(i) for c in cols)) for i in range(n)]
        return tuple(
            HostColumn.from_values(t, [r[j] for r in rows])
            for j, t in enumerate(self.out_types))

    # -- wiring --------------------------------------------------------------
    def set_input(self, *features) -> "MultiOutputHostTransformer":
        super().set_input(*features)
        self._views = None
        self._batch_memo = None
        return self

    def get_outputs(self) -> tuple:
        """The M output features, each backed by a view stage."""
        if not self.out_types:
            raise ValueError(f"{self}: declare out_types")
        if self._views is None:
            views = []
            for j in range(len(self.out_types)):
                v = _MultiOutputView(parent=self, slot=j)
                v.set_input(*self._inputs)
                views.append(v)
            self._views = tuple(views)
        return tuple(v.get_output() for v in self._views)

    def get_output(self):
        raise TypeError(
            f"{type(self).__name__} is multi-output: use get_outputs()")

    # -- batch memo (one computation feeds all views of a layer) -------------
    def _batch_columns(self, data) -> tuple[HostColumn, ...]:
        if self._batch_memo is None or self._batch_memo[0]() is not data:
            cols = [data.host_col(n) for n in self.runtime_input_names()]
            self._batch_memo = (weakref.ref(data),
                                self.host_apply_multi(*cols))
        return self._batch_memo[1]


class _MultiOutputView(HostTransformer):
    """One output slot of a MultiOutputHostTransformer; the DAG-visible
    stage."""

    def __init__(self, parent: Optional[MultiOutputHostTransformer] = None,
                 slot: int = 0, uid: Optional[str] = None):
        self.parent = parent
        self.slot = int(slot)
        if parent is not None:
            self.in_types = parent.in_types
            self.variadic = parent.variadic
            self.out_type = parent.out_types[slot]
            op = f"{parent.operation_name}[{slot}]"
        else:
            op = None
        super().__init__(operation_name=op, uid=uid)

    def _wired_parent(self) -> MultiOutputHostTransformer:
        # a deserialized view owns a fresh parent with no inputs: wire it
        # from the view's own (graph-restored) inputs
        if not self.parent._inputs and self._inputs:
            self.parent._inputs = self._inputs
        return self.parent

    def runtime_input_names(self):
        return self._wired_parent().runtime_input_names() if self.parent \
            else self.input_names

    def output_column(self, data) -> HostColumn:
        return self._wired_parent()._batch_columns(data)[self.slot]

    def transform_row(self, *values):
        return self.parent.transform_row_multi(*values)[self.slot]

    def config(self):
        return {
            "parent_class": type(self.parent).__name__,
            "parent_config": self.parent.config(),
            "parent_uid": self.parent.uid,
            "slot": self.slot,
        }

    @classmethod
    def from_config(cls, config, uid=None):
        parent_uid = config.get("parent_uid")
        parent = _PARENT_CACHE.get(parent_uid) if parent_uid else None
        if parent is None:
            parent_cls = STAGE_REGISTRY[config["parent_class"]]
            parent = parent_cls.from_config(config["parent_config"],
                                            uid=parent_uid)
            if parent_uid:
                _PARENT_CACHE[parent_uid] = parent
        return cls(parent=parent, slot=config["slot"], uid=uid)
