from transmogrifai_tpu.stages.base import (
    Estimator, FeatureGeneratorStage, HostTransformer, DeviceTransformer,
    LambdaTransformer, PipelineStage, Transformer, STAGE_REGISTRY,
)

__all__ = [
    "Estimator", "FeatureGeneratorStage", "HostTransformer",
    "DeviceTransformer", "LambdaTransformer", "PipelineStage", "Transformer",
    "STAGE_REGISTRY",
]
