"""The feature type system: 45 immutable, nullable-aware wrapper types.

Parity: reference ``features/src/main/scala/com/salesforce/op/features/types/``
(`FeatureType.scala:44-116,265-355`, `Numerics.scala`, `Text.scala`, `Maps.scala`,
`Geolocation.scala`, `OPVector.scala`). Same hierarchy, same 45 concrete types,
same mixin semantics (``NonNullable``, ``Categorical``/``SingleResponse``/
``MultiResponse``, ``Location``).

TPU-first divergence: the *device* representation of a column of each type is
fixed-width arrays + validity masks (nullability is a mask, not an Option) —
see ``transmogrifai_tpu.frame``. These Python wrappers exist for (a) row-level
local scoring (`transform_row` parity with the reference's `OpTransformer`),
(b) the testkit generators, and (c) the typed DSL. Hot paths never construct
them; they operate on columnar arrays.
"""

from __future__ import annotations

from typing import Any, ClassVar, Optional

import numpy as np

__all__ = [
    "FeatureType", "FeatureTypeValueError",
    "NonNullable", "Categorical", "SingleResponse", "MultiResponse", "Location",
    # numerics
    "OPNumeric", "Real", "RealNN", "Integral", "Binary", "Date", "DateTime",
    "Currency", "Percent",
    # text
    "Text", "TextArea", "Email", "URL", "Phone", "ID", "PickList", "ComboBox",
    "Base64", "Country", "State", "City", "PostalCode", "Street",
    # collections
    "OPCollection", "OPList", "TextList", "DateList", "DateTimeList",
    "Geolocation", "MultiPickList", "OPVector",
    # maps
    "OPMap", "TextMap", "TextAreaMap", "EmailMap", "URLMap", "PhoneMap",
    "IDMap", "PickListMap", "ComboBoxMap", "Base64Map", "CountryMap",
    "StateMap", "CityMap", "PostalCodeMap", "StreetMap", "RealMap",
    "IntegralMap", "BinaryMap", "CurrencyMap", "PercentMap", "DateMap",
    "DateTimeMap", "MultiPickListMap", "GeolocationMap", "NameStats",
    "Prediction",
    # registry / helpers
    "FEATURE_TYPES", "feature_type_of", "is_subtype",
]


class FeatureTypeValueError(ValueError):
    """Raised when a value does not conform to its feature type."""


class FeatureType:
    """Base of every feature type: an immutable wrapper around an optional value.

    Mirrors reference ``FeatureType`` (value/isEmpty/isNullable/exists/contains).
    """

    __slots__ = ("_value",)

    #: does this type admit an empty value?
    is_nullable: ClassVar[bool] = True
    #: short device-representation kind consumed by the frame layer
    device_kind: ClassVar[str] = "abstract"

    def __init__(self, value: Any = None):
        self._value = self._validate(value)
        if not self.is_nullable and self.is_empty:
            raise FeatureTypeValueError(
                f"{type(self).__name__} cannot be empty (NonNullable)"
            )

    # -- subclass hooks ------------------------------------------------------
    @classmethod
    def _validate(cls, value: Any) -> Any:
        return value

    @classmethod
    def empty_value(cls) -> Any:
        """The canonical empty value (reference ``FeatureTypeDefaults``)."""
        return None

    # -- accessors -----------------------------------------------------------
    @property
    def value(self) -> Any:
        return self._value

    @property
    def is_empty(self) -> bool:
        v = self._value
        if v is None:
            return True
        if isinstance(v, (str,)):
            return False  # empty string is a value, like reference Text("")
        if isinstance(v, (list, tuple, set, frozenset, dict)):
            return len(v) == 0
        if isinstance(v, np.ndarray):
            return v.size == 0
        return False

    def exists(self, predicate) -> bool:
        return (not self.is_empty) and bool(predicate(self._value))

    def contains(self, item: Any) -> bool:
        if self.is_empty:
            return False
        v = self._value
        if isinstance(v, (list, tuple, set, frozenset, dict)):
            return item in v
        return v == item

    @classmethod
    def empty(cls) -> "FeatureType":
        return cls(cls.empty_value())

    # -- equality / repr -----------------------------------------------------
    def __eq__(self, other: Any) -> bool:
        if not isinstance(other, FeatureType):
            return NotImplemented
        if type(self) is not type(other):
            return False
        a, b = self._value, other._value
        if isinstance(a, np.ndarray) or isinstance(b, np.ndarray):
            return bool(np.array_equal(np.asarray(a), np.asarray(b)))
        return a == b

    def __hash__(self) -> int:
        v = self._value
        if isinstance(v, (list, np.ndarray)):
            v = tuple(np.asarray(v).ravel().tolist())
        elif isinstance(v, set):
            v = frozenset(v)
        elif isinstance(v, dict):
            v = tuple(sorted((k, _hashable(x)) for k, x in v.items()))
        return hash((type(self).__name__, v))

    def __repr__(self) -> str:
        return f"{type(self).__name__}({self._value!r})"

    def __bool__(self) -> bool:
        return not self.is_empty


def _hashable(v: Any) -> Any:
    if isinstance(v, (list, np.ndarray)):
        return tuple(np.asarray(v).ravel().tolist())
    if isinstance(v, set):
        return frozenset(v)
    return v


# --------------------------------------------------------------------------
# Mixins (reference FeatureType.scala:118-160)
# --------------------------------------------------------------------------

class NonNullable:
    """Marker: the type never holds an empty value."""
    is_nullable: ClassVar[bool] = False


class Categorical:
    """Marker: values come from a finite vocabulary (pivotable)."""


class SingleResponse(Categorical):
    """Marker: single-response categorical (e.g. PickList)."""


class MultiResponse(Categorical):
    """Marker: multi-response categorical (e.g. MultiPickList)."""


class Location:
    """Marker: geographic types (Country..Street, Geolocation)."""


# --------------------------------------------------------------------------
# Numerics (reference types/Numerics.scala)
# --------------------------------------------------------------------------

class OPNumeric(FeatureType):
    """Abstract numeric; value is Optional[float|int|bool]."""

    def to_double(self) -> Optional[float]:
        return None if self.is_empty else float(self._value)


class Real(OPNumeric):
    device_kind = "real"

    @classmethod
    def _validate(cls, value):
        if value is None:
            return None
        if isinstance(value, (bool, np.bool_)):
            return float(value)
        if isinstance(value, (int, float, np.integer, np.floating)):
            return float(value)
        raise FeatureTypeValueError(f"{cls.__name__} expects a number, got {value!r}")


class RealNN(NonNullable, Real):
    """Non-nullable real (labels, responses)."""
    device_kind = "real"


class Currency(Real):
    device_kind = "real"


class Percent(Real):
    device_kind = "real"


class Integral(OPNumeric):
    device_kind = "integral"

    @classmethod
    def _validate(cls, value):
        if value is None:
            return None
        if isinstance(value, (bool, np.bool_)):
            return int(value)
        if isinstance(value, (int, np.integer)):
            return int(value)
        if isinstance(value, (float, np.floating)) and float(value).is_integer():
            return int(value)
        raise FeatureTypeValueError(f"{cls.__name__} expects an integer, got {value!r}")


class Date(Integral):
    """Epoch millis (day resolution in practice)."""
    device_kind = "date"


class DateTime(Date):
    device_kind = "datetime"


class Binary(SingleResponse, OPNumeric):
    device_kind = "binary"

    @classmethod
    def _validate(cls, value):
        if value is None:
            return None
        if isinstance(value, (bool, np.bool_)):
            return bool(value)
        if isinstance(value, (int, float, np.integer, np.floating)) and value in (0, 1):
            return bool(value)
        raise FeatureTypeValueError(f"{cls.__name__} expects a boolean, got {value!r}")

    def to_double(self) -> Optional[float]:
        return None if self.is_empty else float(self._value)


# --------------------------------------------------------------------------
# Text (reference types/Text.scala)
# --------------------------------------------------------------------------

class Text(FeatureType):
    device_kind = "text"

    @classmethod
    def _validate(cls, value):
        if value is None:
            return None
        if isinstance(value, str):
            return value
        raise FeatureTypeValueError(f"{cls.__name__} expects a string, got {value!r}")


class TextArea(Text):
    """Long free-form text (vectorized by hashing, never pivoted)."""
    device_kind = "textarea"


class Email(Text):
    device_kind = "email"

    def prefix(self) -> Optional[str]:
        v = self._value
        if v is None or "@" not in v:
            return None
        p, _, d = v.partition("@")
        return p if p and d else None

    def domain(self) -> Optional[str]:
        v = self._value
        if v is None or "@" not in v:
            return None
        p, _, d = v.partition("@")
        return d if p and d else None


class URL(Text):
    device_kind = "url"


class Phone(Text):
    device_kind = "phone"


class ID(Text):
    device_kind = "id"


class PickList(SingleResponse, Text):
    device_kind = "picklist"


class ComboBox(Text):
    device_kind = "combobox"


class Base64(Text):
    device_kind = "base64"

    def as_bytes(self) -> Optional[bytes]:
        import base64 as _b64
        return None if self.is_empty else _b64.b64decode(self._value)


class Country(Location, Text):
    device_kind = "country"


class State(Location, Text):
    device_kind = "state"


class City(Location, Text):
    device_kind = "city"


class PostalCode(Location, Text):
    device_kind = "postalcode"


class Street(Location, Text):
    device_kind = "street"


# --------------------------------------------------------------------------
# Collections (reference types/Lists.scala, Geolocation.scala, OPVector.scala)
# --------------------------------------------------------------------------

class OPCollection(FeatureType):
    """Abstract collection; empty collection == empty value."""


class OPList(OPCollection):
    pass


class TextList(OPList):
    device_kind = "textlist"

    @classmethod
    def _validate(cls, value):
        if value is None:
            return []
        if isinstance(value, (list, tuple)):
            out = []
            for x in value:
                if not isinstance(x, str):
                    raise FeatureTypeValueError(f"TextList expects strings, got {x!r}")
                out.append(x)
            return out
        raise FeatureTypeValueError(f"{cls.__name__} expects a list, got {value!r}")

    @classmethod
    def empty_value(cls):
        return []


class DateList(OPList):
    device_kind = "datelist"

    @classmethod
    def _validate(cls, value):
        if value is None:
            return []
        if isinstance(value, (list, tuple)):
            return [int(x) for x in value]
        raise FeatureTypeValueError(f"{cls.__name__} expects a list, got {value!r}")

    @classmethod
    def empty_value(cls):
        return []


class DateTimeList(DateList):
    device_kind = "datetimelist"


class Geolocation(Location, OPList):
    """(lat, lon, accuracy) triple; empty list when absent.

    Parity: reference ``types/Geolocation.scala`` (accuracy is a
    ``GeolocationAccuracy`` ordinal 0-10 there; an int here).
    """
    device_kind = "geolocation"

    @classmethod
    def _validate(cls, value):
        if value is None:
            return []
        value = list(value)
        if len(value) == 0:
            return []
        if len(value) != 3:
            raise FeatureTypeValueError(
                f"Geolocation expects [lat, lon, accuracy], got {value!r}")
        lat, lon, acc = float(value[0]), float(value[1]), float(value[2])
        if not (-90.0 <= lat <= 90.0):
            raise FeatureTypeValueError(f"Invalid latitude {lat}")
        if not (-180.0 <= lon <= 180.0):
            raise FeatureTypeValueError(f"Invalid longitude {lon}")
        return [lat, lon, acc]

    @classmethod
    def empty_value(cls):
        return []

    @property
    def lat(self) -> Optional[float]:
        return None if self.is_empty else self._value[0]

    @property
    def lon(self) -> Optional[float]:
        return None if self.is_empty else self._value[1]

    @property
    def accuracy(self) -> Optional[float]:
        return None if self.is_empty else self._value[2]


class MultiPickList(MultiResponse, OPCollection):
    device_kind = "multipicklist"

    @classmethod
    def _validate(cls, value):
        if value is None:
            return set()
        if isinstance(value, (set, frozenset, list, tuple)):
            out = set()
            for x in value:
                if not isinstance(x, str):
                    raise FeatureTypeValueError(
                        f"MultiPickList expects strings, got {x!r}")
                out.add(x)
            return out
        raise FeatureTypeValueError(f"{cls.__name__} expects a set, got {value!r}")

    @classmethod
    def empty_value(cls):
        return set()


class OPVector(NonNullable, OPCollection):
    """Dense/sparse numeric vector — device-native (float32 ndarray).

    Parity: reference ``types/OPVector.scala`` (wraps Spark ml Vector).
    """
    device_kind = "vector"

    @classmethod
    def _validate(cls, value):
        if value is None:
            return np.zeros((0,), dtype=np.float32)
        arr = np.asarray(value, dtype=np.float32)
        if arr.ndim != 1:
            raise FeatureTypeValueError(f"OPVector expects rank-1, got shape {arr.shape}")
        return arr

    @classmethod
    def empty_value(cls):
        return np.zeros((0,), dtype=np.float32)

    @property
    def is_empty(self) -> bool:
        return False  # like reference: a vector (even length-0) is never "empty"


# --------------------------------------------------------------------------
# Maps (reference types/Maps.scala — 27 types)
# --------------------------------------------------------------------------

class OPMap(OPCollection):
    """Abstract map String -> element; empty map == empty value."""

    #: python type of the map's element values
    element_validator: ClassVar = staticmethod(lambda v: v)

    @classmethod
    def _validate(cls, value):
        if value is None:
            return {}
        if not isinstance(value, dict):
            raise FeatureTypeValueError(f"{cls.__name__} expects a dict, got {value!r}")
        ev = cls.element_validator
        return {str(k): ev(v) for k, v in value.items()}

    @classmethod
    def empty_value(cls):
        return {}


def _text_elem(v):
    if not isinstance(v, str):
        raise FeatureTypeValueError(f"expected str map value, got {v!r}")
    return v


def _real_elem(v):
    return float(v)


def _integral_elem(v):
    return int(v)


def _binary_elem(v):
    if isinstance(v, (bool, np.bool_)):
        return bool(v)
    if v in (0, 1):
        return bool(v)
    raise FeatureTypeValueError(f"expected bool map value, got {v!r}")


def _set_elem(v):
    return set(v)


def _geo_elem(v):
    return Geolocation._validate(v)


class TextMap(OPMap):
    device_kind = "map_text"
    element_validator = staticmethod(_text_elem)


class TextAreaMap(TextMap):
    device_kind = "map_textarea"


class EmailMap(TextMap):
    device_kind = "map_email"


class URLMap(TextMap):
    device_kind = "map_url"


class PhoneMap(TextMap):
    device_kind = "map_phone"


class IDMap(TextMap):
    device_kind = "map_id"


class PickListMap(SingleResponse, TextMap):
    device_kind = "map_picklist"


class ComboBoxMap(TextMap):
    device_kind = "map_combobox"


class Base64Map(TextMap):
    device_kind = "map_base64"


class CountryMap(Location, TextMap):
    device_kind = "map_country"


class StateMap(Location, TextMap):
    device_kind = "map_state"


class CityMap(Location, TextMap):
    device_kind = "map_city"


class PostalCodeMap(Location, TextMap):
    device_kind = "map_postalcode"


class StreetMap(Location, TextMap):
    device_kind = "map_street"


class RealMap(OPMap):
    device_kind = "map_real"
    element_validator = staticmethod(_real_elem)


class CurrencyMap(RealMap):
    device_kind = "map_currency"


class PercentMap(RealMap):
    device_kind = "map_percent"


class IntegralMap(OPMap):
    device_kind = "map_integral"
    element_validator = staticmethod(_integral_elem)


class DateMap(IntegralMap):
    device_kind = "map_date"


class DateTimeMap(DateMap):
    device_kind = "map_datetime"


class BinaryMap(OPMap):
    device_kind = "map_binary"
    element_validator = staticmethod(_binary_elem)


class MultiPickListMap(MultiResponse, OPMap):
    device_kind = "map_multipicklist"
    element_validator = staticmethod(_set_elem)


class GeolocationMap(Location, OPMap):
    device_kind = "map_geolocation"
    element_validator = staticmethod(_geo_elem)


class NameStats(TextMap):
    """Name-detection statistics map (reference types/Maps.scala NameStats
    keys: isName, originalValue, gender)."""
    device_kind = "map_namestats"


class Prediction(NonNullable, RealMap):
    """Model output map with required key ``prediction`` and optional
    ``probability_*`` / ``rawPrediction_*`` keys.

    Parity: reference ``types/Maps.scala`` Prediction (`prediction/probability/
    rawPrediction` accessors, non-nullable).
    """
    device_kind = "prediction"

    PredictionName: ClassVar[str] = "prediction"
    RawPredictionName: ClassVar[str] = "rawPrediction"
    ProbabilityName: ClassVar[str] = "probability"

    @classmethod
    def _validate(cls, value):
        out = super()._validate(value)
        if cls.PredictionName not in out:
            raise FeatureTypeValueError(
                f"Prediction map must contain '{cls.PredictionName}' key, got {value!r}")
        return out

    @classmethod
    def empty_value(cls):
        raise FeatureTypeValueError("Prediction is non-nullable and has no empty value")

    @property
    def prediction(self) -> float:
        return self._value[self.PredictionName]

    def _keyed(self, prefix: str) -> list[float]:
        ks = sorted(
            (k for k in self._value if k.startswith(prefix + "_")),
            key=lambda k: int(k.rsplit("_", 1)[1]),
        )
        return [self._value[k] for k in ks]

    @property
    def raw_prediction(self) -> list[float]:
        return self._keyed(self.RawPredictionName)

    @property
    def probability(self) -> list[float]:
        return self._keyed(self.ProbabilityName)

    @staticmethod
    def make(prediction: float,
             raw_prediction=None,
             probability=None) -> "Prediction":
        m: dict[str, float] = {Prediction.PredictionName: float(prediction)}
        for i, v in enumerate(raw_prediction if raw_prediction is not None else []):
            m[f"{Prediction.RawPredictionName}_{i}"] = float(v)
        for i, v in enumerate(probability if probability is not None else []):
            m[f"{Prediction.ProbabilityName}_{i}"] = float(v)
        return Prediction(m)


# --------------------------------------------------------------------------
# Registry (reference FeatureType.scala:265-355 — featureTypeTags, 45 entries)
# --------------------------------------------------------------------------

FEATURE_TYPES: dict[str, type[FeatureType]] = {
    c.__name__: c
    for c in [
        # vector
        OPVector,
        # lists
        TextList, DateList, DateTimeList, Geolocation,
        # maps
        Base64Map, BinaryMap, ComboBoxMap, CurrencyMap, DateMap, DateTimeMap,
        EmailMap, IDMap, IntegralMap, MultiPickListMap, PercentMap, PhoneMap,
        PickListMap, RealMap, TextAreaMap, TextMap, URLMap, CountryMap,
        StateMap, CityMap, PostalCodeMap, StreetMap, NameStats, GeolocationMap,
        Prediction,
        # numerics
        Binary, Currency, Date, DateTime, Integral, Percent, Real, RealNN,
        # sets
        MultiPickList,
        # text
        Base64, ComboBox, Email, ID, Phone, PickList, Text, TextArea, URL,
        Country, State, City, PostalCode, Street,
    ]
}

# The reference registry (FeatureType.scala:265-355) holds exactly these 53
# concrete entries: 1 vector + 4 lists + 25 maps + 8 numerics + 1 set + 14 text.
assert len(FEATURE_TYPES) == 53, len(FEATURE_TYPES)


def feature_type_of(name: str) -> type[FeatureType]:
    try:
        return FEATURE_TYPES[name]
    except KeyError:
        raise KeyError(
            f"Unknown feature type {name!r}; known: {sorted(FEATURE_TYPES)}") from None


def is_subtype(a: type, b: type) -> bool:
    """``a`` conforms to ``b`` in the feature type lattice."""
    return issubclass(a, b)


def nullable_base(ftype: type) -> type:
    """The nearest nullable ancestor of a feature type (``ftype`` itself
    when already nullable). The serving/explain surfaces build RESPONSE
    raw columns with this: requests legitimately omit the label, and a
    non-nullable type (RealNN) would reject the resulting Nones."""
    if ftype.is_nullable:
        return ftype
    return next(b for b in ftype.__mro__
                if isinstance(b, type) and issubclass(b, FeatureType)
                and b.is_nullable)
