from transmogrifai_tpu.types import feature_types
from transmogrifai_tpu.types.feature_types import *  # noqa: F401,F403
