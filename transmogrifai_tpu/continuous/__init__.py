"""Closed-loop continuous AutoML: stream -> drift -> retrain -> hot-swap.

One long-running supervised control loop (the flagship "millions of
users" scenario, ROADMAP item 3) built from pieces the framework already
has:

- **ingest**: ``readers.streaming.FileStreamingReader`` micro-batches
  with durable ``StreamCheckpoint`` progress (at-least-once replay);
- **drift monitoring** (:mod:`~transmogrifai_tpu.continuous.drift`):
  rolling per-feature reference-vs-live statistics reusing the
  RawFeatureFilter distribution machinery (fill rates, binned
  histograms, JS divergence / PSI, label rate), with hysteresis and
  cooldown so one noisy batch can't trigger a retrain storm;
- **retrain orchestration** (:mod:`~transmogrifai_tpu.continuous.loop`):
  a drift trigger launches a retrain on the accumulated window that
  resumes from the fitted-DAG + sweep + refit checkpoints on
  interruption instead of cold-starting, registers the result in the
  serving ``ModelRegistry``, and promotes it through
  ``FleetServer.hot_swap``'s shadow-parity gate — a failed gate or
  failed retrain leaves the old model serving and backs off;
- **lifecycle + durability** (:mod:`~transmogrifai_tpu.continuous.
  state`): one durable loop manifest (atomic JSON) recording window
  boundaries, trigger decisions, retrain attempts, and promotions, so a
  killed-and-restarted loop resumes with zero lost rows and bounded
  staleness.

Chaos sites ``continuous.ingest|trigger|retrain|promote`` make every
transition injectable (``utils/faults.py``). See docs/CONTINUOUS.md.
"""

from transmogrifai_tpu.continuous.drift import (
    DriftConfig, DriftDecision, DriftMonitor,
)
from transmogrifai_tpu.continuous.loop import ContinuousLoop, ContinuousMetrics
from transmogrifai_tpu.continuous.state import LoopState

__all__ = ["ContinuousLoop", "ContinuousMetrics", "DriftConfig",
           "DriftDecision", "DriftMonitor", "LoopState"]
