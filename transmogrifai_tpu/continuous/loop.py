"""The closed-loop controller: ingest -> drift -> retrain -> hot-swap.

One long-running process (``cli continuous`` / runner ``CONTINUOUS``)
that keeps a serving model fresh against a live stream:

1. **ingest**: ``FileStreamingReader`` micro-batches (durable
   ``StreamCheckpoint`` progress) accumulate into a bounded retrain
   buffer, and every batch folds into the :class:`~transmogrifai_tpu.
   continuous.drift.DriftMonitor`'s live window statistics.
2. **trigger**: every ``window_batches`` batches the window closes and
   is scored against the reference (the serving model's own training
   distribution). Hysteresis + cooldown keep one noisy batch from
   triggering; a trigger writes a durable ``pendingRetrain`` record
   BEFORE any training starts.
3. **retrain**: the workflow refits on the buffered window with a
   per-window ``checkpoint_dir``, so an interrupted attempt resumes
   from the fitted-DAG + sweep + refit checkpoints (PR 3/PR 7) instead
   of cold-starting — a preemption mid-retrain costs only the in-flight
   layer. A failed retrain backs off exponentially (in windows) and the
   old model keeps serving.
4. **promote**: the new model registers as the next version in the
   fleet's ``ModelRegistry`` and promotes through ``FleetServer.
   hot_swap`` — candidate warmup, shadow-parity gate on live rows,
   atomic alias flip, old-lane drain: zero dropped requests by
   construction. A gate rejection ROLLS BACK (old version untouched,
   rollback counted, cooldown armed). On success the drift reference
   rebases onto the retrain window and the buffer clears.

Fault sites ``continuous.ingest`` / ``continuous.trigger`` /
``continuous.retrain`` / ``continuous.promote`` make each transition
chaos-testable; ``serving.swap`` (inside ``hot_swap``) and the reader's
``ingest.read`` compose with them. Durability lives in
:class:`~transmogrifai_tpu.continuous.state.LoopState`: a
killed-and-restarted loop resumes the pending retrain on the SAME rows
(buffer files re-read from the manifest) and loses zero stream rows
(files not yet committed replay via the stream checkpoint).
"""

from __future__ import annotations

import os
import shutil
import threading
import time
import warnings
from typing import Optional

from transmogrifai_tpu.continuous.drift import DriftConfig, DriftMonitor
from transmogrifai_tpu.continuous.state import LoopState
from transmogrifai_tpu.readers.base import CustomReader
from transmogrifai_tpu.readers.streaming import (
    FileStreamingReader, reader_for_file,
)
from transmogrifai_tpu.utils.events import dump_incident, events

__all__ = ["ContinuousLoop", "ContinuousMetrics"]


class ContinuousMetrics:
    """Process-lifetime loop counters (the Prometheus
    ``transmogrifai_continuous_*`` feed; loop-LIFETIME totals that
    survive restarts live in the durable ``LoopState.totals``)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.batches = 0
        self.rows = 0
        self.skipped_batches = 0
        self.drift_triggers = 0
        self.retrains = 0
        self.retrain_failures = 0
        self.promotions = 0
        self.rollbacks = 0

    def record_batch(self, rows: int) -> None:
        with self._lock:
            self.batches += 1
            self.rows += int(rows)

    def record_skipped_batch(self) -> None:
        with self._lock:
            self.skipped_batches += 1

    def record_trigger(self) -> None:
        with self._lock:
            self.drift_triggers += 1

    def record_retrain(self) -> None:
        with self._lock:
            self.retrains += 1

    def record_retrain_failure(self) -> None:
        with self._lock:
            self.retrain_failures += 1

    def record_promotion(self) -> None:
        with self._lock:
            self.promotions += 1

    def record_rollback(self) -> None:
        with self._lock:
            self.rollbacks += 1

    def to_json(self) -> dict:
        with self._lock:
            return {"batches": self.batches, "rows": self.rows,
                    "skippedBatches": self.skipped_batches,
                    "driftTriggers": self.drift_triggers,
                    "retrains": self.retrains,
                    "retrainFailures": self.retrain_failures,
                    "promotions": self.promotions,
                    "rollbacks": self.rollbacks}


class ContinuousLoop:
    """Supervised stream -> drift -> retrain -> hot-swap control loop.

    Usage::

        loop = ContinuousLoop(workflow, stream_dir="incoming/",
                              state_dir="loop_state/",
                              initial_model=model, model_id="live",
                              drift=DriftConfig(js_threshold=0.2),
                              window_batches=4, timeout_s=30.0)
        report = loop.run()

    ``workflow`` is the retrain template: a wired ``Workflow`` whose
    result features define the model; its reader is replaced per retrain
    with the accumulated window. With ``initial_model=None`` the loop
    BOOTSTRAPS: the first full window trains v1 before serving starts.
    Stream files must carry the response column (labeled training data
    arriving continuously); scoring traffic is served concurrently by
    the loop's ``FleetServer`` (``fleet`` / ``metrics_port``).
    """

    def __init__(self, workflow, stream_dir: str, state_dir: str, *,
                 model_id: str = "live",
                 pattern: str = "*",
                 initial_model=None,
                 reference_frame=None,
                 reference_path: Optional[str] = None,
                 drift: Optional[DriftConfig] = None,
                 window_batches: int = 4,
                 max_buffer_batches: int = 8,
                 poll_interval_s: float = 0.5,
                 timeout_s: Optional[float] = None,
                 max_windows: Optional[int] = None,
                 max_retrain_attempts: int = 3,
                 shadow_rows: int = 16,
                 shadow_tolerance: float = 1.0,
                 staleness_bound_s: Optional[float] = None,
                 metrics_port: Optional[int] = None,
                 metrics_host: str = "127.0.0.1",
                 access_log_sample: float = 0.0,
                 slo=None,
                 events_spill: bool = True,
                 fleet=None,
                 stop_fleet_on_exit: bool = True,
                 on_started=None,
                 on_stopping=None,
                 **lane_kwargs):
        """``shadow_tolerance`` defaults LOOSE (1.0): a drift-retrained
        model legitimately scores shifted traffic differently, so the
        gate's default job here is schema/NaN sanity (mismatched keys
        and NaN diffs are +inf, never promotable) — tighten it when
        retrains are expected to be refinements."""
        from transmogrifai_tpu.serving.fleet import FleetServer
        self.workflow = workflow
        self.stream_dir = stream_dir
        self.pattern = pattern
        self.state_dir = state_dir
        self.model_id = model_id
        self.initial_model = initial_model
        self.reference_frame = reference_frame
        #: batch file (csv/avro/parquet) sampling the serving model's
        #: TRAINING data — the file-surface twin of ``reference_frame``
        #: for the CLI/runner, which cannot pass a frame. Without either,
        #: a loop given an initial model ADOPTS the first stream window
        #: as the reference, which reads drift ~0 on a stream that is
        #: already shifted relative to the model
        self.reference_path = reference_path
        self.window_batches = int(window_batches)
        self.max_buffer_batches = max(int(max_buffer_batches),
                                      self.window_batches)
        self.poll_interval_s = float(poll_interval_s)
        self.timeout_s = timeout_s
        self.max_windows = max_windows
        self.max_retrain_attempts = int(max_retrain_attempts)
        self.staleness_bound_s = staleness_bound_s
        self.stop_fleet_on_exit = stop_fleet_on_exit
        #: called once after startup (fleet + scrape endpoint live,
        #: pending retrain resumed) — the CLI's announce hook
        self.on_started = on_started
        #: called once when the stream ends, BEFORE the endpoint/fleet
        #: tear down — lets live-traffic clients quiesce instead of
        #: seeing connection errors from a vanished endpoint
        self.on_stopping = on_stopping

        self.raw_features = workflow.raw_features()
        if not self.raw_features:
            raise ValueError("workflow has no raw features (set result "
                             "features before building the loop)")
        responses = [f.name for f in self.raw_features if f.is_response]
        self.response = responses[0] if responses else None
        #: stream files parse under the MODEL's raw types (the
        #: stream_score schema-pinning rule): per-file inference must
        #: not disagree with the fitted pipeline, and a restart must
        #: re-read buffer files to the exact same rows
        self.schema = {f.name: f.ftype for f in self.raw_features}

        self.metrics = ContinuousMetrics()
        self.monitor = DriftMonitor(drift)
        self.state = LoopState(state_dir, model_id)
        self.fleet = fleet if fleet is not None else FleetServer(
            shadow_rows=shadow_rows, shadow_tolerance=shadow_tolerance,
            **lane_kwargs)
        self._fleet_started = False
        self._metrics_port = metrics_port
        self._metrics_host = metrics_host
        self._access_log_sample = float(access_log_sample)
        self.metrics_http = None
        #: durable flight-recorder spill: events.jsonl under state_dir
        #: (the black box a postmortem greps by trace id / event kind)
        self._events_spill = bool(events_spill)
        self._events_spill_configured = False
        #: SLO engine over the loop's fleet + its own staleness; built
        #: from ``slo`` (objectives list / config path / engine), with a
        #: staleness objective implied by ``staleness_bound_s``
        self.slo_engine = self._build_slo_engine(slo)
        #: source file -> in-memory records of the live buffer (restart
        #: rebuilds from the manifest's file list instead)
        self._rows_by_source: dict[str, list] = {}
        self._batches_in_window = 0
        self._windows_this_run = 0
        self._serving_totals: Optional[dict] = None
        #: degradation ladder (utils/resources.py): after an OOM-failed
        #: retrain the window shrinks to this many NEWEST rows for the
        #: backed-off retry (halved again per OOM) instead of abandoning
        #: the model; reset on the next successful promotion
        self._retrain_row_cap: Optional[int] = None
        #: background host-pressure sampler (RSS + free disk under
        #: state_dir), started with the loop
        self._watchdog = None

    def _build_slo_engine(self, slo):
        if slo is None and self.staleness_bound_s is None:
            return None
        from transmogrifai_tpu.utils.slo import SLObjective, SLOEngine
        engine = SLOEngine.for_serving(
            slo if slo is not None else [],
            lambda: [lane.metrics
                     for lane in self.fleet.active_lanes().values()],
            staleness_fn=self.staleness_s)
        if self.staleness_bound_s is not None and not any(
                o.kind == "staleness" for o in engine.objectives):
            engine.add(SLObjective(name="staleness", kind="staleness",
                                   bound_s=float(self.staleness_bound_s)),
                       value_fn=self.staleness_s)
        return engine

    # -- lifecycle -----------------------------------------------------------
    def run(self) -> dict:
        """Drive the loop until the stream times out, ``max_windows``
        close, or the process dies. Returns :meth:`report`."""
        from transmogrifai_tpu.utils.faults import fault_point
        from transmogrifai_tpu.utils.tracing import span
        with span("continuous.loop", model=self.model_id,
                  stream=self.stream_dir):
            reader = None
            # _startup's side effects (fleet lanes, metrics port, resumed
            # retrain) are inside the try: a failing startup step or
            # on_started hook must still tear down what DID start, or an
            # embedding supervisor's retry inherits bound ports and live
            # lane threads
            try:
                self._startup()
                if self.on_started is not None:
                    self.on_started(self)
                reader = self._make_stream_reader()
                for records in reader.stream():
                    fault_point("continuous.ingest")
                    self._consume_batch(reader.current_file, records)
                    if self._batches_in_window >= self.window_batches:
                        self._close_window()
                        if self.max_windows is not None and \
                                self._windows_this_run >= self.max_windows:
                            break
            except BaseException as e:
                # the daemon is dying with an error (a real crash OR an
                # injected preemption): freeze the black box first —
                # the dump IS the postmortem a restarted-and-healthy
                # process can no longer produce. A graceful Ctrl-C /
                # SystemExit shutdown is NOT an incident: routine
                # restarts must not accumulate fake postmortems.
                if not isinstance(e, (KeyboardInterrupt, SystemExit)):
                    self._incident_dump(
                        "loop_error",
                        {"error": f"{type(e).__name__}: {str(e)[:300]}"})
                raise
            finally:
                if reader is not None:
                    self._stream_skipped = list(reader.skipped_files)
                self._shutdown()
        return self.report()

    def _startup(self) -> None:
        from transmogrifai_tpu.utils.resources import (
            ResourceWatchdog, set_watch_path,
        )
        # the daemon WRITES under state_dir (manifest, checkpoints,
        # spill): point every default pressure probe — /healthz blocks,
        # the disk gauges — at that filesystem, not the cwd's
        set_watch_path(self.state_dir)
        if self._watchdog is None:
            self._watchdog = ResourceWatchdog(self.state_dir).start()
        if self._events_spill and not self._events_spill_configured \
                and not self.state._disabled:
            events.configure(spill_path=os.path.join(
                self.state_dir, "events.jsonl"))
            self._events_spill_configured = True
        # device-stall autopsies freeze their full dumps under the same
        # state_dir/incidents/ every other incident producer here uses
        # (an explicit TRANSMOGRIFAI_DEVICEWATCH_DIR wins; without this
        # a daemon stall would emit only the summary event and discard
        # the thread stacks / ledger / HBM census)
        from transmogrifai_tpu.utils import devicewatch
        if devicewatch.watchdog.incident_dir is None \
                and not self.state._disabled:
            devicewatch.configure(
                incident_dir=self.state_dir,
                scrape_fn=lambda: self._registry().render())
            # ownership marker: _shutdown releases the process-global
            # config (and the closure pinning this loop) so a later
            # loop in the same process can claim it for ITS state_dir
            self._devicewatch_owner = True
        if self.state.drift_reference:
            self.monitor.restore_reference(self.state.drift_reference)
        if self.reference_frame is None and self.reference_path \
                and not self.monitor.has_reference:
            # fail FAST on a bad reference file: it is startup config,
            # and silently falling through to adopt-first-window would
            # blind the monitor to exactly the drift being pinned for
            records = list(reader_for_file(self.reference_path,
                                           self.schema).read())
            self.reference_frame = CustomReader(
                records=records).generate_frame(
                    self._frame_features(records))
        if self.reference_frame is not None \
                and not self.monitor.has_reference:
            self.monitor.set_reference(
                self.reference_frame,
                [f.name for f in self.raw_features],
                response=self.response)
            self.state.drift_reference = self.monitor.reference_to_json()
            self.state.save()
        if not self._has_active():
            # the durable last-promoted version outranks initial_model:
            # after a kill-and-restart the loop must keep serving what
            # it promoted, not regress to the (older) bootstrap model
            self._restore_promoted_model()
        if self.initial_model is not None and not self._has_active():
            self.fleet.register(model=self.initial_model,
                                model_id=self.model_id)
        self._start_fleet_if_serveable()
        if self._metrics_port is not None and self.metrics_http is None:
            from transmogrifai_tpu.serving.http import MetricsServer
            self.metrics_http = MetricsServer(
                render_fn=self._registry().render, health_fn=self.health,
                score_fn=self.fleet._http_score,
                port=self._metrics_port, host=self._metrics_host,
                access_log_sample=self._access_log_sample).start()
        # resume: a pending retrain recorded before the crash re-runs on
        # the SAME rows (manifest file list), resuming from its own
        # fitted-DAG/sweep/refit checkpoints — zero duplicate fits
        if self.state.pending_retrain is not None:
            warnings.warn(
                "continuous loop: resuming pending retrain of window "
                f"{self.state.pending_retrain.get('windowSeq')} "
                f"(attempt {self.state.pending_retrain.get('attempt')})",
                RuntimeWarning)
            self._execute_retrain()

    def _registry(self):
        """The loop's full scrape registry (fleet + continuous + slo
        series) — built once, shared by the HTTP endpoint and incident
        dumps (a dump without ``--metrics-port`` still carries a scrape)."""
        if getattr(self, "_registry_obj", None) is None:
            from transmogrifai_tpu.utils.prometheus import build_registry
            self._registry_obj = build_registry(
                fleet=self.fleet, continuous=self, slo=self.slo_engine)
        return self._registry_obj

    def _incident_dump(self, reason: str,
                       extra: Optional[dict] = None) -> Optional[str]:
        """Write the dump-on-incident snapshot (``utils.events.
        dump_incident``) under ``state_dir/incidents/``. Best-effort by
        construction — observability must never compound the incident."""
        try:
            doc = dict(extra or {})
            doc.setdefault("modelId", self.model_id)
            doc.setdefault("window", self.state.window_seq)
            if self.state.pending_retrain is not None:
                doc.setdefault("pendingRetrain",
                               dict(self.state.pending_retrain))
            return dump_incident(self.state_dir, reason,
                                 scrape_fn=self._registry().render,
                                 extra=doc)
        except Exception as e:  # noqa: BLE001 — see docstring
            warnings.warn(
                f"continuous loop: incident dump failed "
                f"({type(e).__name__}: {e})", RuntimeWarning)
            return None

    def _shutdown(self) -> None:
        if self._watchdog is not None:
            self._watchdog.stop()
            self._watchdog = None
        if self.on_stopping is not None:
            try:
                self.on_stopping(self)
            except Exception as e:  # noqa: BLE001 — a quiesce hook must not block teardown
                warnings.warn(
                    f"continuous loop: on_stopping hook failed "
                    f"({type(e).__name__}: {e})", RuntimeWarning)
        if self._fleet_started:
            # settle counters BEFORE lanes drop (stop() clears them)
            self._serving_totals = self._serving_snapshot()
        if self.metrics_http is not None:
            self.metrics_http.stop()
            self.metrics_http = None
        if self.stop_fleet_on_exit and self._fleet_started:
            self.fleet.stop(drain=True)
            self._fleet_started = False
        if self._events_spill_configured:
            # flush the black box and release the spill file: the NEXT
            # loop (tests, supervisor restarts into a new state dir)
            # must not keep appending into this one's history
            events.configure(spill_path=None)
            self._events_spill_configured = False
        if getattr(self, "_devicewatch_owner", False):
            # release the process-global autopsy config this loop
            # claimed at startup: a later loop (supervisor restart into
            # a NEW state dir) must claim its own incident dir, not dump
            # into this one's — and the scrape closure must not pin the
            # dead loop in memory for the process lifetime
            from transmogrifai_tpu.utils import devicewatch
            devicewatch.watchdog.incident_dir = None
            devicewatch.watchdog.scrape_fn = None
            self._devicewatch_owner = False

    def _has_active(self) -> bool:
        return self.fleet.registry.active_version(self.model_id) is not None

    def _models_root(self) -> str:
        return os.path.join(self.state_dir, "models")

    def _restore_promoted_model(self) -> None:
        """Re-register the durably saved promoted version(s) (written by
        :meth:`_persist_promoted`) and re-activate the one the manifest
        last promoted. Best-effort: a corrupt saved model costs serving
        until the next promotion, never the loop."""
        root = self._models_root()
        if not os.path.isdir(os.path.join(root, self.model_id)):
            return
        try:
            entries = self.fleet.register_dir(root)
            last = self.state.promotions[-1]["version"] \
                if self.state.promotions else None
            if last and any(e.model_id == self.model_id
                            and e.version == last for e in entries):
                self.fleet.registry.promote(self.model_id, last)
        except Exception as e:  # noqa: BLE001 — stale saved model != dead loop
            warnings.warn(
                f"continuous loop: could not restore the promoted model "
                f"from {root!r} ({type(e).__name__}: {e}); serving "
                "resumes at the next promotion", RuntimeWarning)

    def _persist_promoted(self, model, version: str) -> None:
        """Save the just-promoted version under the durable state root
        (and prune superseded version dirs — the fleet unloaded them) so
        a restarted loop keeps serving it. Best-effort."""
        parent = os.path.join(self._models_root(), self.model_id)
        try:
            model.save(os.path.join(parent, version))
            for d in os.listdir(parent):
                if d != version:
                    shutil.rmtree(os.path.join(parent, d),
                                  ignore_errors=True)
        except Exception as e:  # noqa: BLE001 — persistence is redundancy, not the swap
            warnings.warn(
                f"continuous loop: could not persist promoted version "
                f"{version!r} under {parent!r} ({type(e).__name__}: {e});"
                " a restart will not serve it", RuntimeWarning)

    def _start_fleet_if_serveable(self) -> None:
        if not self._fleet_started and self._has_active():
            self.fleet.start()
            self._fleet_started = True

    def _make_stream_reader(self) -> FileStreamingReader:
        return FileStreamingReader(
            self.stream_dir, pattern=self.pattern, schema=self.schema,
            poll_interval_s=self.poll_interval_s,
            timeout_s=self.timeout_s,
            checkpoint=os.path.join(self.state_dir, "stream.json"))

    # -- ingest --------------------------------------------------------------
    def _consume_batch(self, source: Optional[str], records: list) -> None:
        from transmogrifai_tpu.utils.faults import FaultHarnessError
        from transmogrifai_tpu.utils.tracing import span
        try:
            with span("continuous.ingest", source=source,
                      rows=len(records)):
                frame = CustomReader(records=records).generate_frame(
                    self._frame_features(records))
                if self.monitor.has_reference:
                    self.monitor.observe(frame)
        except FaultHarnessError:
            raise  # injected crash / misconfigured plan: die and resume
        except Exception as e:  # noqa: BLE001 — isolate one poison batch
            # a malformed batch must not kill a loop whose serving is
            # healthy: drop it FROM TRAINING (counted + warned — operators
            # watch skippedBatches for silent data loss), keep streaming
            self.metrics.record_skipped_batch()
            warnings.warn(
                f"continuous loop: dropping unreadable batch from "
                f"{source!r} ({type(e).__name__}: {e})", RuntimeWarning)
            return
        self.metrics.record_batch(len(records))
        if source is not None:
            # at-least-once replay: a restarted stream may re-yield the
            # in-flight file — replace its buffer entry, never duplicate
            self._rows_by_source[source] = list(records)
            self.state.buffer = [b for b in self.state.buffer
                                 if b.get("file") != source]
            for stale in set(self._rows_by_source) - {
                    b.get("file") for b in self.state.buffer} - {source}:
                self._rows_by_source.pop(stale, None)
        self.state.record_batch(source, len(records),
                                self.max_buffer_batches)
        self._batches_in_window += 1

    def _frame_features(self, records: list) -> list:
        """Raw features present in this batch (the response is optional
        on a pure scoring stream; predictors are required)."""
        if records and isinstance(records[0], dict) \
                and self.response is not None \
                and self.response not in records[0]:
            return [f for f in self.raw_features if not f.is_response]
        return list(self.raw_features)

    # -- window + trigger ----------------------------------------------------
    def _close_window(self) -> None:
        from transmogrifai_tpu.utils.faults import fault_point
        self._batches_in_window = 0
        self._windows_this_run += 1
        fault_point("continuous.trigger")
        if not self.monitor.has_reference:
            self._baseline_window()
            return
        decision = self.monitor.close_window()
        # refresh the persisted monitor state (breach streak, cooldown,
        # window counter) so a kill between two breaching windows
        # doesn't reset hysteresis and delay the trigger
        self.state.drift_reference = self.monitor.reference_to_json()
        self.state.record_decision(decision.to_json())
        if decision.triggered:
            self.metrics.record_trigger()
            events.emit("continuous.drift_trigger",
                        model=self.model_id,
                        window=self.state.window_seq,
                        reasons=list(decision.reasons))
            warnings.warn(
                f"continuous loop: drift trigger at window "
                f"{self.state.window_seq}: {'; '.join(decision.reasons)}",
                RuntimeWarning)
            if self.state.pending_retrain is None:
                ckpt = os.path.join(
                    self.state_dir, f"retrain_w{self.state.window_seq}")
                self.state.begin_retrain(decision.reasons, ckpt)
                self._execute_retrain()
                return
        if self.state.pending_retrain is not None \
                and self.state.retrain_eligible():
            # a previously failed retrain retries (resuming from its
            # checkpoints) once its backoff expires
            self.state.begin_retrain([], None)
            self._execute_retrain()

    def _baseline_window(self) -> None:
        """First window with no reference: bootstrap-train the initial
        model from it (no model yet), or adopt it as the reference for
        an externally supplied model."""
        rows = self._buffer_rows_list()
        if not rows:
            return
        if not self._has_active():
            if not self.state.retrain_eligible():
                # a failed bootstrap train is backing off: count the
                # window (backoff is measured in windows — skipping the
                # increment would deadlock eligibility) and keep
                # buffering instead of re-running the failing train
                # every window
                self.state.window_seq += 1
                self.state.save()
                return
            ckpt = os.path.join(
                self.state_dir, f"retrain_w{self.state.window_seq}")
            self.state.window_seq += 1
            self.state.begin_retrain(["bootstrap"], ckpt)
            self._execute_retrain()
            return
        frame = CustomReader(records=rows).generate_frame(
            self.raw_features)
        self.monitor.set_reference(frame,
                                   [f.name for f in self.raw_features],
                                   response=self.response)
        self.state.drift_reference = self.monitor.reference_to_json()
        self.state.window_seq += 1
        self.state.save()
        warnings.warn(
            "continuous loop: adopted the first stream window as the "
            "drift reference (pass reference_frame= to pin the training "
            "distribution instead)", RuntimeWarning)

    # -- retrain -------------------------------------------------------------
    def _buffer_rows_list(self) -> list:
        rows: list = []
        for b in self.state.buffer:
            src = b.get("file")
            if src is not None and src in self._rows_by_source:
                rows.extend(self._rows_by_source[src])
        return rows

    def _window_rows(self, pending: dict) -> list:
        """The pending retrain's rows: the in-memory buffer when it
        covers the recorded files, else a re-read of the manifest's file
        list (the restart path — same files, same schema, same rows)."""
        files = [f for f in pending.get("files", []) if f]
        rows: list = []
        for f in files:
            if f in self._rows_by_source:
                rows.extend(self._rows_by_source[f])
                continue
            try:
                file_rows = list(reader_for_file(f, self.schema).read())
            except Exception as e:  # noqa: BLE001 — a rotated file costs rows, not the loop
                warnings.warn(
                    f"continuous loop: retrain window file {f!r} is "
                    f"unreadable on resume ({type(e).__name__}: {e}); "
                    "retraining without it", RuntimeWarning)
                continue
            self._rows_by_source[f] = file_rows
            rows.extend(file_rows)
        if self._retrain_row_cap is not None \
                and len(rows) > self._retrain_row_cap:
            # degradation ladder: a previous attempt OOMed — train the
            # retry on the NEWEST cap rows (freshest data wins when the
            # window must shrink)
            rows = rows[-self._retrain_row_cap:]
        return rows

    def _execute_retrain(self) -> bool:
        from transmogrifai_tpu.utils.faults import (
            FaultHarnessError, fault_point,
        )
        from transmogrifai_tpu.utils.profiling import OpStep, profiler
        from transmogrifai_tpu.utils.tracing import span
        pending = self.state.pending_retrain
        if pending is None:
            return False
        self.metrics.record_retrain()
        events.emit("continuous.retrain", model=self.model_id,
                    window=pending.get("windowSeq"),
                    attempt=pending.get("attempt"),
                    rows=pending.get("rows"),
                    reasons=list(pending.get("reason", [])))
        with span("continuous.retrain",
                  window=pending.get("windowSeq"),
                  attempt=pending.get("attempt"),
                  rows=pending.get("rows")):
            rows = self._window_rows(pending)
            if not rows:
                warnings.warn(
                    "continuous loop: pending retrain has no recoverable "
                    "rows (buffer files gone); abandoning it",
                    RuntimeWarning)
                self.state.abandon_retrain("no recoverable window rows")
                events.emit("continuous.retrain_failed",
                            model=self.model_id,
                            window=pending.get("windowSeq"),
                            abandoned=True,
                            error="no recoverable window rows")
                self._incident_dump("retrain_abandoned",
                                    {"why": "no recoverable window rows",
                                     "retrain": dict(pending)})
                self._cleanup_retrain_dir(pending)
                return False
            try:
                # chaos seam: a preemption here dies with the
                # pendingRetrain manifest already durable — the restarted
                # loop re-runs this retrain on the same rows, resuming
                # from its checkpoints; an io/transient fault follows the
                # failed-attempt backoff path below
                fault_point("continuous.retrain")
                self.workflow.set_input_records(rows)
                with profiler.phase(OpStep.MODEL_TRAINING):
                    model = self.workflow.train(
                        checkpoint_dir=pending.get("checkpointDir"))
            except FaultHarnessError:
                raise  # preemption dies; the pending record resumes it
            except Exception as e:  # noqa: BLE001 — a failed retrain must not stop serving
                self._maybe_shrink_retrain_window(len(rows), e)
                self._retrain_failed(pending, e)
                return False
        return self._promote(model, pending, rows)

    def _maybe_shrink_retrain_window(self, n_rows: int,
                                     err: BaseException) -> None:
        """Degradation ladder (utils/resources.py): an OOM-failed retrain
        halves the row window for the backed-off retry — the loop keeps
        working toward a fresh model on the freshest half instead of
        re-OOMing the identical shape until the attempt budget abandons
        it. The old model keeps serving throughout (the existing failed-
        retrain contract); the cap resets on the next promotion."""
        from transmogrifai_tpu.utils.resources import (
            is_resource_exhausted, ladder_enabled, record_degradation,
        )
        if not ladder_enabled() or not is_resource_exhausted(err):
            return
        cap = max(n_rows // 2, 1)
        if self._retrain_row_cap is not None:
            cap = min(cap, max(self._retrain_row_cap // 2, 1))
        self._retrain_row_cap = cap
        record_degradation("continuous.retrain", f"rows_{cap}", error=err,
                           model=self.model_id, windowRows=n_rows)

    def _retrain_failed(self, pending: dict, err: BaseException) -> None:
        self.metrics.record_retrain_failure()
        abandoned = pending.get("attempt", 1) >= self.max_retrain_attempts
        events.emit("continuous.retrain_failed", model=self.model_id,
                    window=pending.get("windowSeq"),
                    attempt=pending.get("attempt"),
                    abandoned=abandoned,
                    error=f"{type(err).__name__}: {str(err)[:200]}")
        warnings.warn(
            f"continuous loop: retrain attempt "
            f"{pending.get('attempt')} failed ({type(err).__name__}: "
            f"{str(err)[:200]}); old model keeps serving",
            RuntimeWarning)
        self.state.record_retrain_failure(
            f"{type(err).__name__}: {str(err)[:300]}")
        if abandoned:
            self.state.abandon_retrain(
                f"attempt budget ({self.max_retrain_attempts}) exhausted")
            self.monitor.start_cooldown()
            self._incident_dump(
                "retrain_abandoned",
                {"why": f"attempt budget ({self.max_retrain_attempts}) "
                        "exhausted",
                 "error": f"{type(err).__name__}: {str(err)[:300]}",
                 "retrain": dict(pending)})
            # the pending record is gone, so nothing will ever resume
            # from (or clean up) its checkpoint tree — delete it now or
            # a forever-running daemon leaks one dir per abandoned
            # retrain under the durable state root
            self._cleanup_retrain_dir(pending)

    # -- promote -------------------------------------------------------------
    def _promote(self, model, pending: dict, rows: list) -> bool:
        from transmogrifai_tpu.serving.fleet import ShadowParityError
        from transmogrifai_tpu.utils.faults import (
            FaultHarnessError, fault_point,
        )
        from transmogrifai_tpu.utils.tracing import span
        fault_point("continuous.promote")
        with span("continuous.promote", model=self.model_id,
                  window=pending.get("windowSeq")):
            try:
                if not self._has_active():
                    # bootstrap: first version of the endpoint — nothing
                    # to swap, registration activates and serving starts
                    entry = self.fleet.register(model=model,
                                                model_id=self.model_id)
                    self._start_fleet_if_serveable()
                    version = entry.version
                    swap_report = {"modelId": self.model_id,
                                   "toVersion": version,
                                   "bootstrap": True}
                else:
                    swap_report = self.fleet.hot_swap(self.model_id,
                                                      model=model)
                    version = swap_report["toVersion"]
            except ShadowParityError as e:
                # the parity gate REJECTED the candidate: the old version
                # never stopped serving; count the rollback, cool down
                self.metrics.record_rollback()
                self.state.record_rollback(
                    {"error": f"ShadowParityError: {e}"})
                self.monitor.start_cooldown()
                warnings.warn(
                    f"continuous loop: promotion rolled back by the "
                    f"shadow parity gate ({e}); old version keeps "
                    "serving", RuntimeWarning)
                # the fleet already emitted fleet.gate_rejected; the
                # dump freezes it together with the triggering drift
                # event and the retrain lineage still in the ring
                self._incident_dump(
                    "gate_rejected",
                    {"maxAbsDiff": e.max_abs_diff,
                     "retrain": dict(pending),
                     "error": str(e)[:300]})
                self._cleanup_retrain_dir(pending)
                return False
            except FaultHarnessError:
                raise
            except Exception as e:  # noqa: BLE001 — an aborted swap leaves the old version serving
                self._retrain_failed(pending, e)
                return False
            staleness = None
            if pending.get("triggeredAt"):
                staleness = time.time() - float(pending["triggeredAt"])
            if self.staleness_bound_s is not None and staleness is not None \
                    and staleness > self.staleness_bound_s:
                warnings.warn(
                    f"continuous loop: promotion staleness "
                    f"{staleness:.1f}s exceeds the "
                    f"{self.staleness_bound_s:.1f}s bound", RuntimeWarning)
            self._persist_promoted(model, version)
            # rebase drift on the data the NEW serving model saw
            frame = CustomReader(records=rows).generate_frame(
                self.raw_features)
            self.monitor.set_reference(
                frame, [f.name for f in self.raw_features],
                response=self.response)
            self.monitor.start_cooldown()
            self.state.drift_reference = self.monitor.reference_to_json()
            self.state.record_promotion(version, swap_report, staleness)
            self.metrics.record_promotion()
            #: a successful promotion clears the OOM row cap — the next
            #: retrain starts from the full buffer window again
            self._retrain_row_cap = None
            # the LINEAGE event: any scored response stamped with this
            # (model, version, fingerprint) traces back through it to the
            # drift window, the retrain attempt, and the exact stream
            # files whose rows trained the serving model
            try:
                fingerprint = self.fleet.registry.get(
                    self.model_id, version).fingerprint
            except Exception:  # noqa: BLE001 — lineage is best-effort metadata
                fingerprint = swap_report.get("fingerprint")
            events.emit(
                "continuous.promoted", model=self.model_id,
                version=version, fingerprint=fingerprint,
                window=pending.get("windowSeq"),
                reasons=list(pending.get("reason", [])),
                attempt=pending.get("attempt"),
                rows=len(rows),
                files=[f for f in pending.get("files", []) if f],
                stalenessSeconds=(round(staleness, 3)
                                  if staleness is not None else None),
                fromVersion=swap_report.get("fromVersion"))
            self._rows_by_source = {}
            self._cleanup_retrain_dir(pending)
        return True

    @staticmethod
    def _cleanup_retrain_dir(pending: dict) -> None:
        ckpt = pending.get("checkpointDir")
        if ckpt and os.path.isdir(ckpt):
            shutil.rmtree(ckpt, ignore_errors=True)

    # -- observability -------------------------------------------------------
    def drift_scores(self) -> dict:
        return self.monitor.drift_scores()

    def staleness_s(self) -> float:
        """Age of the serving model's training data (seconds since the
        last promotion; 0 before any promotion)."""
        if self.state.last_promoted_at is None:
            return 0.0
        return max(0.0, time.time() - self.state.last_promoted_at)

    def window_seq(self) -> int:
        return self.state.window_seq

    def buffer_rows(self) -> int:
        return sum(int(b.get("rows", 0)) for b in self.state.buffer)

    def _serving_snapshot(self) -> dict:
        admitted = completed = failed = 0
        for lane in self.fleet.active_lanes().values():
            doc = lane.metrics.snapshot(mirror_to_profiler=False)
            admitted += doc["requests"]["admitted"]
            completed += doc["requests"]["completed"]
            failed += doc["requests"]["failed"]
        return {"admitted": admitted, "completed": completed,
                "failed": failed}

    def health(self) -> dict:
        doc = self.fleet.health() if self._fleet_started else {
            "status": "warming", "models": {}, "ready": False}
        doc["loop"] = {"window": self.state.window_seq,
                       "bufferRows": self.buffer_rows(),
                       "pendingRetrain": self.state.pending_retrain
                       is not None,
                       "counters": self.metrics.to_json()}
        # host pressure on the loop's /healthz watches the LOOP's write
        # root (state_dir) — overriding the fleet's default-path block:
        # the disk that matters is the one the manifest/checkpoints/
        # spill land on
        from transmogrifai_tpu.utils.resources import pressure_state
        doc["resources"] = pressure_state(self.state_dir)
        # the loop's engine outranks the fleet's (the fleet only has one
        # when constructed with slo=; the loop composes staleness in)
        from transmogrifai_tpu.utils.slo import fold_health
        fold_health(self.slo_engine, doc)
        return doc

    def report(self) -> dict:
        """One JSON document summarizing the run (the runner/CLI result
        body and the bench harness's source of truth)."""
        doc = {
            "modelId": self.model_id,
            "activeVersion": self.fleet.registry.active_version(
                self.model_id),
            "windows": self.state.window_seq,
            "counters": self.metrics.to_json(),
            "totals": dict(self.state.totals),
            "promotions": list(self.state.promotions),
            "retrainFailures": list(self.state.retrain_failures),
            "pendingRetrain": self.state.pending_retrain,
            "driftScores": self.drift_scores(),
            "lastDecision": (self.state.decisions[-1]
                             if self.state.decisions else None),
            "stalenessSeconds": round(self.staleness_s(), 3),
            "streamSkippedFiles": list(
                getattr(self, "_stream_skipped", [])),
        }
        if self._serving_totals is not None:
            doc["serving"] = dict(self._serving_totals)
        elif self._fleet_started:
            doc["serving"] = self._serving_snapshot()
        return doc
