"""Drift monitoring: rolling reference-vs-live feature statistics.

The pre-training ``RawFeatureFilter`` already knows how to summarize a
feature as a :class:`~transmogrifai_tpu.filters.raw_feature_filter.
FeatureDistribution` (fill rate + binned histogram: numeric bins over a
fixed range, hashed-token bins for text) and how to compare two of them
(Jensen-Shannon divergence). The drift monitor reuses exactly that
machinery ONLINE: a **reference** distribution per feature (captured
from the data the serving model was trained on) against a **live**
distribution accumulated over the current micro-batch window. Because
the reference's numeric range pins the live binning, histograms from
different batches merge by simple addition (the monoid the reference's
map-reduce design already guarantees), and out-of-range live mass lands
in the edge bins — which *is* the covariate shift being measured.

Per-feature scores each window:

- ``js`` — JS divergence of the binned distributions (0..1, log2);
- ``psi`` — population stability index over the same bins (the industry
  drift score; unbounded, > 0.25 conventionally "major shift");
- ``fillDelta`` — |reference fill rate - live fill rate|;
- ``labelDelta`` — |reference label mean - live label mean| (when the
  response is numeric and present in the stream).

Trigger policy = thresholds + **hysteresis** (``consecutive_windows``
breaching windows required — one noisy batch cannot fire) + **cooldown**
(``cooldown_windows`` after any trigger/promotion during which no new
trigger fires — a slow retrain cannot be re-triggered into a storm).
"""

from __future__ import annotations

import warnings
from dataclasses import dataclass, field
from typing import Optional, Sequence

import numpy as np

from transmogrifai_tpu.filters.raw_feature_filter import (
    FeatureDistribution, _distribution,
)
from transmogrifai_tpu.frame import HostFrame, NUMERIC_KINDS

__all__ = ["DriftConfig", "DriftDecision", "DriftMonitor", "psi"]


def psi(ref: FeatureDistribution, live: FeatureDistribution,
        eps: float = 1e-4) -> float:
    """Population stability index over aligned histogram bins.
    Zero-mass bins are floored at ``eps`` (the standard smoothing) so a
    bin that appears only in production contributes a large-but-finite
    term instead of infinity."""
    p, q = ref.distribution, live.distribution
    ps, qs = p.sum(), q.sum()
    if ps == 0 or qs == 0 or p.shape != q.shape:
        return 0.0
    p = np.maximum(p / ps, eps)
    q = np.maximum(q / qs, eps)
    return float(np.sum((q - p) * np.log(q / p)))


@dataclass
class DriftConfig:
    """Thresholds and trigger policy for :class:`DriftMonitor`."""

    #: histogram bins (numeric ranges / hashed token buckets)
    bins: int = 32
    #: drift metric driving the trigger: "js" or "psi"
    metric: str = "js"
    #: per-feature JS divergence above this breaches (metric="js")
    js_threshold: float = 0.25
    #: per-feature PSI above this breaches (metric="psi")
    psi_threshold: float = 0.25
    #: |train fill - live fill| above this breaches
    fill_delta_threshold: float = 0.25
    #: |train label mean - live label mean| above this breaches (numeric
    #: response only; None disables)
    label_delta_threshold: Optional[float] = 0.25
    #: hysteresis: consecutive breaching windows required to trigger
    consecutive_windows: int = 2
    #: windows after a trigger/promotion during which triggers are
    #: suppressed (the retrain-storm guard)
    cooldown_windows: int = 2
    #: monitor only these features (default: every non-response raw)
    features: Optional[Sequence[str]] = None

    def __post_init__(self):
        if self.metric not in ("js", "psi"):
            raise ValueError(f"drift metric {self.metric!r}: 'js' or 'psi'")
        if self.consecutive_windows < 1:
            raise ValueError("consecutive_windows must be >= 1")


@dataclass
class DriftDecision:
    """One window's evaluation: per-feature scores + the trigger verdict."""

    window: int
    #: breaching thresholds this window (pre-hysteresis)
    breached: bool
    #: breached for ``consecutive_windows`` in a row and not cooling down
    triggered: bool
    #: feature -> {"js": .., "psi": .., "fillDelta": .., "breached": ..}
    scores: dict = field(default_factory=dict)
    #: human-readable breach reasons (feature: metric value > threshold)
    reasons: list = field(default_factory=list)
    #: live rows the window aggregated
    rows: int = 0
    #: windows left before triggers re-arm (0 = armed)
    cooldown_left: int = 0

    def to_json(self) -> dict:
        return {"window": self.window, "breached": self.breached,
                "triggered": self.triggered, "rows": self.rows,
                "cooldownLeft": self.cooldown_left,
                "reasons": list(self.reasons),
                "scores": {k: dict(v) for k, v in self.scores.items()}}


class _Accum:
    """Mergeable live accumulation of one feature's window distribution."""

    __slots__ = ("count", "nulls", "hist")

    def __init__(self):
        self.count = 0
        self.nulls = 0
        self.hist: Optional[np.ndarray] = None

    def add(self, dist: FeatureDistribution) -> None:
        self.count += dist.count
        self.nulls += dist.nulls
        if self.hist is None:
            self.hist = dist.distribution.astype(float).copy()
        elif self.hist.shape == dist.distribution.shape:
            self.hist += dist.distribution
        # shape mismatch (a column changed kind mid-stream): keep the
        # existing accumulation — fill rates still track, and the next
        # reference rebase realigns the histograms

    def as_distribution(self, name: str) -> FeatureDistribution:
        hist = self.hist if self.hist is not None else np.zeros(1)
        return FeatureDistribution(name, self.count, self.nulls, hist, {})


class DriftMonitor:
    """Reference-vs-live drift scoring over micro-batch windows.

    Usage::

        monitor = DriftMonitor(DriftConfig(js_threshold=0.2))
        monitor.set_reference(train_frame, feature_names, response="label")
        ...
        monitor.observe(batch_frame)        # every micro-batch
        decision = monitor.close_window()   # every window_batches batches
        if decision.triggered: ...          # launch retrain
    """

    def __init__(self, config: Optional[DriftConfig] = None):
        self.config = config or DriftConfig()
        #: feature -> reference FeatureDistribution
        self.reference: dict[str, FeatureDistribution] = {}
        #: reference numeric (min, max) pinning live binning per feature
        self._ranges: dict[str, tuple[float, float]] = {}
        self._response: Optional[str] = None
        self._ref_label_mean: Optional[float] = None
        self._accum: dict[str, _Accum] = {}
        self._label_sum = 0.0
        self._label_n = 0
        self._rows = 0
        self._window = 0
        self._breach_streak = 0
        self._cooldown_left = 0
        #: last close_window() scores (the Prometheus gauge feed)
        self.last_scores: dict[str, dict] = {}

    # -- reference -----------------------------------------------------------
    def set_reference(self, frame: HostFrame,
                      feature_names: Optional[Sequence[str]] = None,
                      response: Optional[str] = None) -> None:
        """(Re)base the reference on ``frame`` — the data the currently
        serving model was trained on. Called at loop start and again on
        every promotion, so drift is always measured against the live
        model's own training distribution."""
        cfg = self.config
        names = list(feature_names if feature_names is not None
                     else frame.names())
        if cfg.features is not None:
            allowed = set(cfg.features)
            names = [n for n in names if n in allowed]
        self._response = response
        self.reference = {}
        self._ranges = {}
        self._ref_label_mean = None
        for name in names:
            if name == response or name not in frame:
                continue
            dist = _distribution(frame[name], name, cfg.bins)
            self.reference[name] = dist
            if "min" in dist.summary:
                self._ranges[name] = (dist.summary["min"],
                                      dist.summary["max"])
        if response is not None and response in frame \
                and frame[response].kind in NUMERIC_KINDS:
            col = frame[response]
            vals = col.values[col.mask] if col.mask is not None \
                else col.values
            if len(vals):
                self._ref_label_mean = float(np.mean(vals))
        self.reset_window()

    @property
    def has_reference(self) -> bool:
        return bool(self.reference)

    # -- live accumulation ---------------------------------------------------
    def observe(self, frame: HostFrame) -> None:
        """Fold one micro-batch into the current window's accumulators."""
        if not self.reference:
            return
        for name, ref in self.reference.items():
            if name not in frame:
                continue
            dist = _distribution(frame[name], name, self.config.bins,
                                 self._ranges.get(name))
            self._accum.setdefault(name, _Accum()).add(dist)
        resp = self._response
        if self._ref_label_mean is not None and resp is not None \
                and resp in frame and frame[resp].kind in NUMERIC_KINDS:
            col = frame[resp]
            vals = col.values[col.mask] if col.mask is not None \
                else col.values
            self._label_sum += float(np.sum(vals))
            self._label_n += int(len(vals))
        self._rows += frame.n_rows

    def reset_window(self) -> None:
        self._accum = {}
        self._label_sum = 0.0
        self._label_n = 0
        self._rows = 0

    # -- evaluation ----------------------------------------------------------
    def window_scores(self) -> dict[str, dict]:
        """Per-feature scores of the CURRENT (possibly partial) window."""
        cfg = self.config
        out: dict[str, dict] = {}
        for name, ref in self.reference.items():
            acc = self._accum.get(name)
            if acc is None or acc.count == 0:
                continue
            live = acc.as_distribution(name)
            js = ref.js_divergence(live) if ref.distribution.size > 1 \
                else 0.0
            p = psi(ref, live) if ref.distribution.size > 1 else 0.0
            fill_delta = abs(ref.fill_rate - live.fill_rate)
            breached, why = self._feature_breach(name, js, p, fill_delta)
            out[name] = {"js": round(js, 6), "psi": round(p, 6),
                         "fillDelta": round(fill_delta, 6),
                         "breached": breached}
            if why:
                out[name]["reason"] = why
        if self._ref_label_mean is not None and self._label_n > 0 \
                and cfg.label_delta_threshold is not None:
            delta = abs(self._label_sum / self._label_n
                        - self._ref_label_mean)
            breached = delta > cfg.label_delta_threshold
            doc = {"js": 0.0, "psi": 0.0, "fillDelta": 0.0,
                   "labelDelta": round(delta, 6), "breached": breached}
            if breached:
                doc["reason"] = (f"label mean delta {delta:.4f} > "
                                 f"{cfg.label_delta_threshold}")
            out["__label__"] = doc
        return out

    def _feature_breach(self, name: str, js: float, p: float,
                        fill_delta: float) -> tuple[bool, Optional[str]]:
        cfg = self.config
        if cfg.metric == "js" and js > cfg.js_threshold:
            return True, (f"{name}: JS divergence {js:.4f} > "
                          f"{cfg.js_threshold}")
        if cfg.metric == "psi" and p > cfg.psi_threshold:
            return True, f"{name}: PSI {p:.4f} > {cfg.psi_threshold}"
        if fill_delta > cfg.fill_delta_threshold:
            return True, (f"{name}: fill delta {fill_delta:.4f} > "
                          f"{cfg.fill_delta_threshold}")
        return False, None

    def close_window(self) -> DriftDecision:
        """Evaluate the accumulated window, apply hysteresis + cooldown,
        and reset the accumulators for the next window."""
        from transmogrifai_tpu.utils.tracing import span
        cfg = self.config
        self._window += 1
        with span("continuous.drift", window=self._window,
                  rows=self._rows, metric=cfg.metric):
            scores = self.window_scores()
            reasons = [d["reason"] for d in scores.values()
                       if d.get("reason")]
            breached = any(d["breached"] for d in scores.values())
            if self._rows == 0:
                breached = False  # an empty window measures nothing
            self._breach_streak = self._breach_streak + 1 if breached \
                else 0
            cooling = self._cooldown_left > 0
            if cooling:
                self._cooldown_left -= 1
            triggered = (not cooling
                         and self._breach_streak >= cfg.consecutive_windows)
            if triggered:
                self._breach_streak = 0
                self.start_cooldown()
            if breached and cooling:
                warnings.warn(
                    f"drift: window {self._window} breached during "
                    f"cooldown ({self._cooldown_left + 1} window(s) "
                    "left); trigger suppressed", RuntimeWarning)
            decision = DriftDecision(
                window=self._window, breached=breached,
                triggered=triggered, scores=scores, reasons=reasons,
                rows=self._rows, cooldown_left=self._cooldown_left)
        self.last_scores = scores
        self.reset_window()
        return decision

    def start_cooldown(self) -> None:
        """Arm the cooldown (called on trigger and on promotion): no
        trigger fires for the next ``cooldown_windows`` windows."""
        self._cooldown_left = max(self._cooldown_left,
                                  self.config.cooldown_windows)

    # -- durability ----------------------------------------------------------
    def reference_to_json(self) -> dict:
        """Serializable reference state (persisted in the loop manifest so
        a restarted loop measures drift against the SAME baseline instead
        of silently rebasing on post-drift data)."""
        return {
            "response": self._response,
            "refLabelMean": self._ref_label_mean,
            "window": self._window,
            "breachStreak": self._breach_streak,
            "cooldownLeft": self._cooldown_left,
            "features": {
                name: {"count": d.count, "nulls": d.nulls,
                       "hist": d.distribution.tolist(),
                       "summary": {k: float(v)
                                   for k, v in d.summary.items()}}
                for name, d in self.reference.items()},
        }

    def restore_reference(self, doc: dict) -> bool:
        """Rebuild the reference from :meth:`reference_to_json` output.
        Malformed state warns and returns False (the loop rebases on the
        next window instead of crashing)."""
        try:
            reference = {}
            ranges = {}
            for name, d in dict(doc.get("features", {})).items():
                dist = FeatureDistribution(
                    name, int(d["count"]), int(d["nulls"]),
                    np.asarray(d["hist"], dtype=float),
                    dict(d.get("summary", {})))
                reference[name] = dist
                if "min" in dist.summary:
                    ranges[name] = (dist.summary["min"],
                                    dist.summary["max"])
        except Exception as e:  # noqa: BLE001 — stale state costs a rebase, never a crash
            warnings.warn(f"drift: unreadable reference state "
                          f"({type(e).__name__}: {e}); rebasing on the "
                          "next window", RuntimeWarning)
            return False
        self.reference = reference
        self._ranges = ranges
        self._response = doc.get("response")
        self._ref_label_mean = doc.get("refLabelMean")
        self._window = int(doc.get("window", 0))
        self._breach_streak = int(doc.get("breachStreak", 0))
        self._cooldown_left = int(doc.get("cooldownLeft", 0))
        self.reset_window()
        return bool(reference)

    # -- observability -------------------------------------------------------
    def drift_scores(self) -> dict[str, float]:
        """feature -> last closed window's driving metric value (the
        ``transmogrifai_continuous_drift_score`` gauge feed)."""
        key = self.config.metric
        out = {}
        for name, d in self.last_scores.items():
            out[name] = d.get("labelDelta", d.get(key, 0.0)) \
                if name == "__label__" else d.get(key, 0.0)
        return out

    def to_json(self) -> dict:
        return {"window": self._window,
                "breachStreak": self._breach_streak,
                "cooldownLeft": self._cooldown_left,
                "referenceFeatures": sorted(self.reference),
                "lastScores": {k: dict(v)
                               for k, v in self.last_scores.items()}}
