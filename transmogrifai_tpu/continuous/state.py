"""Loop lifecycle + durability: one manifest for the whole control loop.

``continuous_manifest.json`` (atomic via ``utils.durable``, best-effort
like every other checkpoint format) is the single source of truth a
killed-and-restarted loop resumes from:

- **window boundaries**: the buffer of micro-batch files currently
  accumulated toward the next retrain (each with its committed row
  count), plus the running window sequence number;
- **trigger decisions**: the last drift decisions (bounded history) and
  the serialized drift REFERENCE, so a restarted loop keeps measuring
  against the pre-drift baseline;
- **retrain attempts**: a ``pendingRetrain`` record written BEFORE the
  retrain starts — window id, exact file list, attempt count, the
  per-window checkpoint directory — so a preemption mid-retrain resumes
  the SAME retrain (same rows, same fitted-DAG/sweep/refit checkpoints)
  instead of losing it;
- **promotions**: every promoted version with its trigger window and
  measured staleness.

Composition with the stream checkpoint: rows live either in files the
``StreamCheckpoint`` has NOT marked done (replayed by the reader on
restart) or in buffer files this manifest lists (re-read directly on
restart) — so a crash at any point loses zero rows (at-least-once).
"""

from __future__ import annotations

import json
import os
import time
import warnings
from typing import Optional

__all__ = ["LoopState", "LOOP_MANIFEST"]

LOOP_MANIFEST = "continuous_manifest.json"
FORMAT_VERSION = 1

#: bounded history kept in the manifest (the loop runs forever; the
#: manifest must not grow with it)
MAX_HISTORY = 50


class LoopState:
    """Durable, resumable state of one :class:`~transmogrifai_tpu.
    continuous.loop.ContinuousLoop`."""

    def __init__(self, path: str, model_id: str):
        from transmogrifai_tpu.utils.durable import ensure_checkpoint_dir
        self.path = path
        self.model_id = model_id
        self.window_seq = 0
        #: [{"file": path, "rows": n}] — the accumulated retrain window
        self.buffer: list[dict] = []
        #: in-flight retrain record (None when idle); see begin_retrain
        self.pending_retrain: Optional[dict] = None
        self.promotions: list[dict] = []
        self.retrain_failures: list[dict] = []
        self.decisions: list[dict] = []
        #: serialized DriftMonitor reference (reference_to_json)
        self.drift_reference: Optional[dict] = None
        #: loop-lifetime totals (survive restarts, unlike ContinuousMetrics)
        self.totals: dict = {k: 0 for k in (
            "batches", "rows", "driftTriggers", "retrains",
            "retrainFailures", "promotions", "rollbacks")}
        self.last_promoted_at: Optional[float] = None
        #: windows to skip retrying a failed retrain (exponential backoff)
        self.backoff_windows = 0
        self.backoff_until_window = 0
        self._disabled = not ensure_checkpoint_dir(path, "continuous loop")
        if not self._disabled:
            self._load()

    # -- io ------------------------------------------------------------------
    def _manifest_path(self) -> str:
        return os.path.join(self.path, LOOP_MANIFEST)

    def _load(self) -> None:
        path = self._manifest_path()
        if not os.path.exists(path):
            return
        try:
            with open(path) as fh:
                doc = json.load(fh)
            if doc.get("formatVersion") != FORMAT_VERSION:
                raise ValueError(
                    f"format {doc.get('formatVersion')!r} != "
                    f"{FORMAT_VERSION}")
        except Exception as e:  # noqa: BLE001 — corrupt manifest != crash
            warnings.warn(
                f"continuous loop: unreadable manifest at {path!r} "
                f"({type(e).__name__}: {e}); starting fresh",
                RuntimeWarning)
            return
        if doc.get("modelId") != self.model_id:
            warnings.warn(
                f"continuous loop: manifest at {path!r} belongs to model "
                f"{doc.get('modelId')!r}, not {self.model_id!r}; "
                "starting fresh", RuntimeWarning)
            return
        self.window_seq = int(doc.get("windowSeq", 0))
        self.buffer = [dict(b) for b in doc.get("buffer", [])]
        self.pending_retrain = doc.get("pendingRetrain")
        self.promotions = list(doc.get("promotions", []))
        self.retrain_failures = list(doc.get("retrainFailures", []))
        self.decisions = list(doc.get("decisions", []))
        self.drift_reference = doc.get("driftReference")
        self.totals.update(doc.get("totals", {}))
        self.last_promoted_at = doc.get("lastPromotedAt")
        self.backoff_windows = int(doc.get("backoffWindows", 0))
        self.backoff_until_window = int(doc.get("backoffUntilWindow", 0))

    def to_json(self) -> dict:
        return {
            "formatVersion": FORMAT_VERSION,
            "modelId": self.model_id,
            "windowSeq": self.window_seq,
            "buffer": [dict(b) for b in self.buffer],
            "pendingRetrain": self.pending_retrain,
            "promotions": self.promotions[-MAX_HISTORY:],
            "retrainFailures": self.retrain_failures[-MAX_HISTORY:],
            "decisions": self.decisions[-MAX_HISTORY:],
            "driftReference": self.drift_reference,
            "totals": dict(self.totals),
            "lastPromotedAt": self.last_promoted_at,
            "backoffWindows": self.backoff_windows,
            "backoffUntilWindow": self.backoff_until_window,
        }

    def save(self) -> bool:
        """Persist the manifest (atomic + best-effort: the loop whose
        actual work is healthy never dies for bookkeeping)."""
        from transmogrifai_tpu.utils.durable import (
            atomic_json_dump, best_effort_checkpoint_write,
        )
        if self._disabled:
            return False
        return best_effort_checkpoint_write(
            lambda: atomic_json_dump(self.to_json(), self._manifest_path()),
            f"continuous loop: manifest write to "
            f"{self._manifest_path()!r} failed; a restart may replay "
            "recent windows")

    # -- transitions ---------------------------------------------------------
    def record_batch(self, source: Optional[str], rows: int,
                     max_buffer_batches: int) -> None:
        """One consumed micro-batch: append to the retrain buffer (bounded
        — the oldest batch falls off a full buffer) and bump totals."""
        self.totals["batches"] += 1
        self.totals["rows"] += rows
        self.buffer.append({"file": source, "rows": int(rows)})
        if len(self.buffer) > max_buffer_batches:
            self.buffer = self.buffer[-max_buffer_batches:]
        self.save()

    def record_decision(self, decision_doc: dict) -> None:
        self.window_seq += 1
        if decision_doc.get("triggered"):
            self.totals["driftTriggers"] += 1
        self.decisions.append(decision_doc)
        self.decisions = self.decisions[-MAX_HISTORY:]
        self.save()

    def begin_retrain(self, reason: list, checkpoint_dir: str) -> dict:
        """Record the retrain BEFORE it starts: the exact buffer file
        list + per-window checkpoint dir are what a preempted process
        needs to resume the same retrain on the same rows."""
        if self.pending_retrain is not None:
            pending = self.pending_retrain
            pending["attempt"] = int(pending.get("attempt", 1)) + 1
        else:
            pending = {
                "windowSeq": self.window_seq,
                "files": [b["file"] for b in self.buffer
                          if b.get("file")],
                "rows": sum(int(b.get("rows", 0)) for b in self.buffer),
                "reason": list(reason),
                "attempt": 1,
                "checkpointDir": checkpoint_dir,
                "triggeredAt": time.time(),
            }
            self.pending_retrain = pending
        self.totals["retrains"] += 1
        self.save()
        return pending

    def record_retrain_failure(self, error: str) -> None:
        """A failed attempt: keep the pending record (the next eligible
        window retries, resuming from the same checkpoints) and back off
        exponentially in windows."""
        self.totals["retrainFailures"] += 1
        self.retrain_failures.append({
            "windowSeq": self.window_seq, "error": error,
            "at": time.time(),
            "attempt": (self.pending_retrain or {}).get("attempt", 1)})
        self.retrain_failures = self.retrain_failures[-MAX_HISTORY:]
        self.backoff_windows = max(1, self.backoff_windows * 2) \
            if self.backoff_windows else 1
        self.backoff_until_window = self.window_seq + self.backoff_windows
        self.save()

    def abandon_retrain(self, why: str) -> None:
        """Give up on the pending retrain (attempt budget exhausted or a
        parity-gate rollback): the old model keeps serving."""
        if self.pending_retrain is not None:
            self.retrain_failures.append({
                "windowSeq": self.window_seq, "error": why,
                "abandoned": True, "at": time.time(),
                "attempt": self.pending_retrain.get("attempt", 1)})
            self.retrain_failures = self.retrain_failures[-MAX_HISTORY:]
        self.pending_retrain = None
        self.save()

    def record_rollback(self, detail: dict) -> None:
        self.totals["rollbacks"] += 1
        self.abandon_retrain(detail.get("error", "rollback"))

    def record_promotion(self, version: str, swap_report: dict,
                         staleness_s: Optional[float]) -> dict:
        """A successful hot-swap: clear the pending retrain + buffer (its
        rows are IN the new model), reset backoff, stamp staleness."""
        doc = {"version": version,
               "windowSeq": (self.pending_retrain or {}).get(
                   "windowSeq", self.window_seq),
               "at": time.time(),
               "stalenessSeconds": (round(staleness_s, 3)
                                    if staleness_s is not None else None),
               "swap": dict(swap_report)}
        self.totals["promotions"] += 1
        self.promotions.append(doc)
        self.promotions = self.promotions[-MAX_HISTORY:]
        self.pending_retrain = None
        self.buffer = []
        self.backoff_windows = 0
        self.backoff_until_window = 0
        self.last_promoted_at = doc["at"]
        self.save()
        return doc

    def retrain_eligible(self) -> bool:
        """True when a pending retrain may (re)run this window (attempt
        budget is the loop's call; backoff is ours)."""
        return self.window_seq >= self.backoff_until_window
