"""RawFeatureFilter: pre-training raw-feature quality gate.

Parity: reference ``core/src/main/scala/com/salesforce/op/filters/
RawFeatureFilter.scala:90-636`` (+ ``FeatureDistribution``, ``Summary``,
``RawFeatureFilterResults``) — compares **training vs scoring** raw feature
distributions and drops features failing:

- training fill rate < ``min_fill``
- |train fill - scoring fill| > ``max_fill_difference``
- max/min fill ratio > ``max_fill_ratio_diff``
- Jensen-Shannon divergence of the binned distributions > ``max_js_divergence``
- null-indicator <-> label correlation > ``max_correlation_null_label``

Distributions are monoid summaries: numerics bin into histograms over the
training min/max range (two passes, like the reference's Summary-then-
Distribution map-reduces); text hashes tokens into a fixed number of bins.
Without a scoring reader only the fill-rate and null-label-correlation
checks apply. The resulting blocklist feeds the workflow's DAG rewiring.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Sequence

import numpy as np

from transmogrifai_tpu.frame import (
    HostColumn, HostFrame, MAP_KINDS, NUMERIC_KINDS, TEXT_KINDS,
)
from transmogrifai_tpu.ops.vectorizers.hashing import (
    _native, encode_ascii_rows, hash_token, tokenize,
)

__all__ = ["FeatureDistribution", "RawFeatureFilter", "RawFeatureFilterResults"]


def _text_hist_native(col: HostColumn, bins: int
                      ) -> Optional[tuple[np.ndarray, int]]:
    """(histogram, nulls) for a text column via the C++ corpus pass (the
    vectorizer's loader/encoder — one tokenizer contract), or None when the
    column needs the Python path (non-string/ASCII rows)."""
    lib = _native()
    if lib is None:
        return None
    encoded = encode_ascii_rows(col.values)
    if encoded is None:
        return None
    buf, offsets, nulls = encoded
    hist = np.zeros(bins, dtype=np.float64)
    lib.hash_tokens_hist(buf, offsets, np.int64(len(col)), np.int32(bins),
                         np.int32(1), hist)
    return hist, nulls


@dataclass
class FeatureDistribution:
    name: str
    count: int
    nulls: int
    distribution: np.ndarray          # binned histogram (un-normalized)
    summary: dict = field(default_factory=dict)

    @property
    def fill_rate(self) -> float:
        return 1.0 - self.nulls / max(self.count, 1)

    def js_divergence(self, other: "FeatureDistribution") -> float:
        p, q = self.distribution, other.distribution
        ps, qs = p.sum(), q.sum()
        if ps == 0 or qs == 0:
            return 0.0
        p, q = p / ps, q / qs
        m = 0.5 * (p + q)

        def kl(a, b):
            with np.errstate(divide="ignore", invalid="ignore"):
                t = np.where(a > 0, a * np.log2(a / b), 0.0)
            return t.sum()

        return float(0.5 * kl(p, m) + 0.5 * kl(q, m))


@dataclass
class RawFeatureFilterResults:
    exclusion_reasons: dict = field(default_factory=dict)  # name -> [reasons]
    train_distributions: dict = field(default_factory=dict)
    score_distributions: dict = field(default_factory=dict)
    #: per-key exclusions for map features (reference RawFeatureFilter's
    #: per-key map blocklist, ``RawFeatureFilter.scala:90-636``):
    #: feature name -> {key -> [reasons]}
    map_key_exclusion_reasons: dict = field(default_factory=dict)

    @property
    def map_key_blocklist(self) -> dict:
        """feature name -> sorted excluded keys (consumed by the workflow's
        map-vectorizer rewiring, the ``setBlocklist`` analog)."""
        return {name: sorted(keys)
                for name, keys in self.map_key_exclusion_reasons.items()
                if keys}

    def to_json(self) -> dict:
        return {
            "exclusionReasons": {k: list(v)
                                 for k, v in self.exclusion_reasons.items()},
            "mapKeyExclusionReasons": {
                name: {k: list(v) for k, v in keys.items()}
                for name, keys in self.map_key_exclusion_reasons.items()},
            "trainFillRates": {k: d.fill_rate
                               for k, d in self.train_distributions.items()},
            "scoreFillRates": {k: d.fill_rate
                               for k, d in self.score_distributions.items()},
        }


def _numeric_hist(vals: np.ndarray, bins: int,
                  rng_minmax: Optional[tuple[float, float]]
                  ) -> tuple[np.ndarray, dict]:
    """Shared numeric binning (whole-feature AND per-map-key paths): clip so
    out-of-range scoring mass lands in the edge bins instead of silently
    vanishing (it IS the distribution shift)."""
    lo, hi = rng_minmax if rng_minmax else (
        (float(vals.min()), float(vals.max())) if vals.size else (0.0, 1.0))
    if hi <= lo:
        hi = lo + 1.0
    hist, _ = np.histogram(np.clip(vals, lo, hi), bins=bins, range=(lo, hi))
    return hist.astype(float), {"min": lo, "max": hi,
                                "mean": float(vals.mean())
                                if vals.size else 0.0}


def _token_hist(values, bins: int) -> np.ndarray:
    """Shared hashed-token histogram for text-ish values (lists tokenize
    element-wise; scalars through the shared tokenizer)."""
    hist = np.zeros(bins, dtype=float)
    for v in values:
        toks = (list(v) if isinstance(v, (list, set, tuple))
                else tokenize(str(v)))
        for t in toks:
            hist[hash_token(str(t), bins)] += 1.0
    return hist


def _distribution(col: HostColumn, name: str, bins: int,
                  rng_minmax: Optional[tuple[float, float]] = None
                  ) -> FeatureDistribution:
    n = len(col)
    kind = col.kind
    if kind in NUMERIC_KINDS:
        mask = col.mask
        vals = col.values[mask]
        nulls = int(n - mask.sum())
        if kind == "binary":
            hist = np.asarray([(vals == 0).sum(), (vals == 1).sum()], float)
            summary = {"min": 0.0, "max": 1.0}
        else:
            hist, summary = _numeric_hist(vals, bins, rng_minmax)
        return FeatureDistribution(name, n, nulls, hist, summary)
    if kind in TEXT_KINDS or kind == "textlist":
        # hot path: one native C pass tokenizes + CRC-hashes the whole
        # column into the corpus histogram (the reference's map-reduce text
        # distribution, RawFeatureFilter.scala:137-199, without the per-row
        # Python loop); list-valued / non-ASCII columns fall back
        native = _text_hist_native(col, bins)
        if native is not None:
            hist, nulls = native
            return FeatureDistribution(name, n, nulls, hist, {})
        present = [v for v in col.values
                   if not (v is None or (isinstance(v, list) and not v))]
        hist = _token_hist(present, bins)
        return FeatureDistribution(name, n, n - len(present), hist, {})
    # everything else: fill-rate-only distribution
    nulls = 0
    for i in range(n):
        v = col.python_value(i)
        if v is None or (hasattr(v, "__len__") and len(v) == 0):
            nulls += 1
    return FeatureDistribution(name, n, nulls, np.zeros(1), {})


_NUMERIC_MAP_KINDS = frozenset({
    "map_real", "map_currency", "map_percent", "map_integral",
    "map_date", "map_datetime"})


def _map_key_distributions(col: HostColumn, bins: int,
                           rng_of: Optional[dict] = None
                           ) -> dict[str, FeatureDistribution]:
    """Per-key FeatureDistributions of a map column (reference
    ``PreparedFeatures.scala`` key-expansion: each key is scored like a
    scalar feature — count is the ROW count, a row missing the key counts
    as null for that key)."""
    n = len(col)
    kind = col.kind
    per_key: dict[str, list] = {}
    for m in col.values:
        for k, v in (m or {}).items():
            if v is not None:
                per_key.setdefault(str(k), []).append(v)
    out: dict[str, FeatureDistribution] = {}
    for k, vals in per_key.items():
        nulls = n - len(vals)
        if kind in _NUMERIC_MAP_KINDS:
            arr = np.asarray([float(v) for v in vals], dtype=float)
            hist, summary = _numeric_hist(arr, bins, (rng_of or {}).get(k))
            out[k] = FeatureDistribution(k, n, nulls, hist, summary)
        elif kind == "map_binary":
            arr = np.asarray([bool(v) for v in vals])
            hist = np.asarray([(~arr).sum(), arr.sum()], float)
            out[k] = FeatureDistribution(k, n, nulls, hist, {})
        else:  # text-ish values: hashed token histogram
            out[k] = FeatureDistribution(k, n, nulls,
                                         _token_hist(vals, bins), {})
    return out


class RawFeatureFilter:
    def __init__(self,
                 scoring_reader=None,
                 bins: int = 100,
                 min_fill: float = 0.001,
                 max_fill_difference: float = 0.9,
                 max_fill_ratio_diff: float = 20.0,
                 max_js_divergence: float = 0.9,
                 max_correlation_null_label: float = 0.9,
                 protected_features: Sequence[str] = ()):
        self.scoring_reader = scoring_reader
        self.bins = bins
        self.min_fill = min_fill
        self.max_fill_difference = max_fill_difference
        self.max_fill_ratio_diff = max_fill_ratio_diff
        self.max_js_divergence = max_js_divergence
        self.max_correlation_null_label = max_correlation_null_label
        self.protected_features = set(protected_features)
        self.results = RawFeatureFilterResults()

    def filter_frame(self, frame: HostFrame, raw_features
                     ) -> tuple[HostFrame, list[str]]:
        # fresh results per run: stale per-key exclusions from a previous
        # train must not leak into (and permanently blocklist keys of) a
        # retrain on refreshed data
        self.results = RawFeatureFilterResults()
        reasons: dict[str, list[str]] = {}
        responses = {f.name for f in raw_features if f.is_response}
        y = None
        for rname in responses:
            if rname in frame and frame[rname].kind in NUMERIC_KINDS:
                y = frame[rname].values
                break

        score_frame = None
        if self.scoring_reader is not None:
            predictors = [f for f in raw_features if not f.is_response]
            score_frame = self.scoring_reader.generate_frame(predictors)

        for f in raw_features:
            name = f.name
            if name in responses or name in self.protected_features:
                continue
            col = frame[name]
            train_dist = _distribution(col, name, self.bins)
            self.results.train_distributions[name] = train_dist
            why: list[str] = []
            if train_dist.fill_rate < self.min_fill:
                why.append(f"training fill rate {train_dist.fill_rate:.4f} "
                           f"< {self.min_fill}")
            # null indicator <-> label correlation
            if y is not None and col.mask is not None:
                null_ind = (~col.mask).astype(float)
                if 0.0 < null_ind.mean() < 1.0 and np.std(y) > 0:
                    c = abs(float(np.corrcoef(null_ind, y)[0, 1]))
                    if c > self.max_correlation_null_label:
                        why.append(
                            f"null-indicator label correlation {c:.3f} > "
                            f"{self.max_correlation_null_label}")
            if score_frame is not None and name in score_frame:
                rng = None
                if "min" in train_dist.summary:
                    rng = (train_dist.summary["min"], train_dist.summary["max"])
                score_dist = _distribution(score_frame[name], name, self.bins,
                                           rng)
                self.results.score_distributions[name] = score_dist
                ft_, fs = train_dist.fill_rate, score_dist.fill_rate
                if abs(ft_ - fs) > self.max_fill_difference:
                    why.append(f"fill difference |{ft_:.3f}-{fs:.3f}| > "
                               f"{self.max_fill_difference}")
                ratio = (max(ft_, fs) / min(ft_, fs)) if min(ft_, fs) > 0 \
                    else float("inf")
                if ratio > self.max_fill_ratio_diff:
                    why.append(f"fill ratio {ratio:.2f} > "
                               f"{self.max_fill_ratio_diff}")
                js = train_dist.js_divergence(score_dist)
                if train_dist.distribution.size > 1 \
                        and js > self.max_js_divergence:
                    why.append(f"JS divergence {js:.3f} > "
                               f"{self.max_js_divergence}")
            if why:
                reasons[name] = why
            elif col.kind in MAP_KINDS:
                # per-key pass (reference RawFeatureFilter.scala:90-636
                # per-key map exclusions): each key is checked like a scalar
                # feature; failing keys go to the map-key blocklist the
                # workflow feeds into the map vectorizers, so one bad key
                # doesn't kill the whole map
                key_reasons = self._check_map_keys(
                    col, score_frame[name]
                    if score_frame is not None and name in score_frame
                    else None, y)
                if key_reasons:
                    seen_keys = {str(k) for m in col.values
                                 for k in (m or {})}
                    if seen_keys and set(key_reasons) >= seen_keys:
                        reasons[name] = [
                            "every map key excluded: "
                            + "; ".join(f"{k}: {v[0]}"
                                        for k, v in key_reasons.items())]
                    else:
                        self.results.map_key_exclusion_reasons[name] = \
                            key_reasons

        self.results.exclusion_reasons = reasons
        blocklist = sorted(reasons)
        return frame.drop(blocklist), blocklist

    def _check_map_keys(self, col: HostColumn,
                        score_col: Optional[HostColumn], y) -> dict:
        """{key: [reasons]} for one map column (train vs optional scoring)."""
        train = _map_key_distributions(col, self.bins)
        rng_of = {k: (d.summary["min"], d.summary["max"])
                  for k, d in train.items() if "min" in d.summary}
        score = (_map_key_distributions(score_col, self.bins, rng_of)
                 if score_col is not None else {})
        # ONE row pass builds every key's absence indicator (a per-key
        # re-scan would be O(keys x rows) interpreter work)
        absent_of: dict[str, np.ndarray] = {}
        if y is not None and float(np.std(y)) > 0:
            n = len(col)
            absent_of = {k: np.ones(n, dtype=np.float64) for k in train}
            for r, m in enumerate(col.values):
                for k, v in (m or {}).items():
                    if v is not None and k in absent_of:
                        absent_of[k][r] = 0.0
        out: dict[str, list[str]] = {}
        for k, td in train.items():
            why: list[str] = []
            if td.fill_rate < self.min_fill:
                why.append(f"training fill rate {td.fill_rate:.4f} < "
                           f"{self.min_fill}")
            if k in absent_of and 0 < td.nulls < td.count:
                c = abs(float(np.corrcoef(absent_of[k], y)[0, 1]))
                if c > self.max_correlation_null_label:
                    why.append(f"null-indicator label correlation {c:.3f} > "
                               f"{self.max_correlation_null_label}")
            sd = score.get(k)
            if score_col is not None:
                ft_ = td.fill_rate
                fs = sd.fill_rate if sd is not None else 0.0
                if abs(ft_ - fs) > self.max_fill_difference:
                    why.append(f"fill difference |{ft_:.3f}-{fs:.3f}| > "
                               f"{self.max_fill_difference}")
                ratio = (max(ft_, fs) / min(ft_, fs)) if min(ft_, fs) > 0 \
                    else float("inf")
                if ratio > self.max_fill_ratio_diff:
                    why.append(f"fill ratio {ratio:.2f} > "
                               f"{self.max_fill_ratio_diff}")
                if sd is not None and td.distribution.size > 1:
                    js = td.js_divergence(sd)
                    if js > self.max_js_divergence:
                        why.append(f"JS divergence {js:.3f} > "
                                   f"{self.max_js_divergence}")
            if why:
                out[k] = why
        return out
