from transmogrifai_tpu.filters.raw_feature_filter import (
    FeatureDistribution, RawFeatureFilter, RawFeatureFilterResults,
)

__all__ = ["FeatureDistribution", "RawFeatureFilter", "RawFeatureFilterResults"]
