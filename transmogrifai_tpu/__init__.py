"""TransmogrifAI-TPU: a TPU-native AutoML framework for structured data.

A ground-up JAX/XLA re-design of the capability set of Salesforce
TransmogrifAI (reference: /root/reference, Scala/Spark). The reference's
essence — a typed feature algebra compiled to a stage DAG, level-scheduled
fit/transform over immutable data, monoid-style distributed statistics, a
model-selection sweep, and provenance metadata driving validation and
explainability — is re-expressed TPU-first:

- columnar host frame -> sharded device frame (pytrees of arrays + validity
  masks, `jax.sharding.NamedSharding` over a `Mesh`)
- stages are pure functions; same-DAG-layer transformers fuse into one
  jitted program per layer
- statistics are monoid pytrees reduced with `lax.psum` across the mesh
- the ModelSelector's k-fold x hyperparameter sweep trains candidates as a
  stacked leading axis under `vmap`/`shard_map` instead of a thread pool

Nothing here is a port of Spark; see SURVEY.md for the layer mapping.
"""

__version__ = "0.1.0"

# Lazy top-level API: submodules import on first attribute access so that the
# foundation layers remain importable while upper layers are under build.
_LAZY = {
    "UID": ("transmogrifai_tpu.uid", "UID"),
    "ft": ("transmogrifai_tpu.types", "feature_types"),
    "Feature": ("transmogrifai_tpu.features.feature", "Feature"),
    "FeatureLike": ("transmogrifai_tpu.features.feature", "FeatureLike"),
    "FeatureBuilder": ("transmogrifai_tpu.features.builder", "FeatureBuilder"),
    "Workflow": ("transmogrifai_tpu.workflow", "Workflow"),
    "WorkflowModel": ("transmogrifai_tpu.workflow", "WorkflowModel"),
    "HostFrame": ("transmogrifai_tpu.frame", "HostFrame"),
}

__all__ = list(_LAZY) + ["__version__"]


def __getattr__(name):
    if name in _LAZY:
        import importlib
        module, attr = _LAZY[name]
        return getattr(importlib.import_module(module), attr)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
