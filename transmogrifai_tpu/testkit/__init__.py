from transmogrifai_tpu.testkit.random_data import (
    RandomBinary, RandomIntegral, RandomList, RandomMap, RandomMultiPickList,
    RandomReal, RandomText, RandomVector,
)
from transmogrifai_tpu.testkit.test_feature_builder import TestFeatureBuilder

__all__ = [
    "RandomBinary", "RandomIntegral", "RandomList", "RandomMap",
    "RandomMultiPickList", "RandomReal", "RandomText", "RandomVector",
    "TestFeatureBuilder",
]
