from transmogrifai_tpu.testkit.random_data import (
    RandomBinary, RandomGeolocation, RandomIntegral, RandomList, RandomMap,
    RandomMultiPickList, RandomReal, RandomSet, RandomText, RandomVector,
)
from transmogrifai_tpu.testkit.test_feature_builder import TestFeatureBuilder

__all__ = [
    "RandomBinary", "RandomGeolocation", "RandomIntegral", "RandomList",
    "RandomMap", "RandomMultiPickList", "RandomReal", "RandomSet",
    "RandomText", "RandomVector", "TestFeatureBuilder",
]
