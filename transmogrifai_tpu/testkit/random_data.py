"""Seeded random feature-data generators.

Parity: reference ``testkit/src/main/scala/com/salesforce/op/testkit/
Random{Text,Real,Integral,Binary,List,Map,Set,Vector}.scala`` — infinite
deterministic generators per feature type with a probability of empty,
``.limit(n)`` to materialize.
"""

from __future__ import annotations

import string
from typing import Any, Callable, Iterator, Optional, Sequence

import numpy as np

__all__ = ["RandomReal", "RandomIntegral", "RandomBinary", "RandomText",
           "RandomList", "RandomMultiPickList", "RandomMap", "RandomVector",
           "RandomGeolocation", "RandomSet"]

_COUNTRIES = ["USA", "Canada", "Mexico", "Brazil", "France", "Germany",
              "Japan", "India", "China", "Australia", "Kenya", "Egypt"]
_CITIES = ["San Francisco", "New York", "Paris", "Berlin", "Tokyo", "Delhi",
           "Shanghai", "Sydney", "Nairobi", "Cairo", "Toronto", "Recife"]
_STATES = ["CA", "NY", "TX", "WA", "OR", "NV", "AZ", "CO", "IL", "MA"]
_EMAILS = ["example.com", "corp.org", "mail.net", "io.dev"]


class _Gen:
    """Infinite seeded generator with probability-of-empty."""

    def __init__(self, sample: Callable[[np.random.Generator], Any],
                 seed: int = 42, prob_empty: float = 0.0):
        self._sample = sample
        self._seed = seed
        self.prob_empty = prob_empty

    def with_prob_of_empty(self, p: float) -> "_Gen":
        return _Gen(self._sample, self._seed, p)

    def reseed(self, seed: int) -> "_Gen":
        return _Gen(self._sample, seed, self.prob_empty)

    def __iter__(self) -> Iterator[Any]:
        rng = np.random.default_rng(self._seed)
        while True:
            if self.prob_empty > 0 and rng.uniform() < self.prob_empty:
                yield None
            else:
                yield self._sample(rng)

    def limit(self, n: int) -> list:
        it = iter(self)
        return [next(it) for _ in range(n)]


class RandomReal:
    @staticmethod
    def normal(mean: float = 0.0, sigma: float = 1.0, seed: int = 42) -> _Gen:
        return _Gen(lambda r: float(r.normal(mean, sigma)), seed)

    @staticmethod
    def uniform(low: float = 0.0, high: float = 1.0, seed: int = 42) -> _Gen:
        return _Gen(lambda r: float(r.uniform(low, high)), seed)

    @staticmethod
    def poisson(lam: float = 1.0, seed: int = 42) -> _Gen:
        return _Gen(lambda r: float(r.poisson(lam)), seed)

    @staticmethod
    def logNormal(mean: float = 0.0, sigma: float = 1.0, seed: int = 42) -> _Gen:
        return _Gen(lambda r: float(r.lognormal(mean, sigma)), seed)

    @staticmethod
    def exponential(scale: float = 1.0, seed: int = 42) -> _Gen:
        return _Gen(lambda r: float(r.exponential(scale)), seed)

    @staticmethod
    def gamma(shape: float = 2.0, scale: float = 1.0, seed: int = 42) -> _Gen:
        return _Gen(lambda r: float(r.gamma(shape, scale)), seed)

    @staticmethod
    def weibull(a: float = 1.5, seed: int = 42) -> _Gen:
        return _Gen(lambda r: float(r.weibull(a)), seed)

    @staticmethod
    def currencies(mean: float = 100.0, sigma: float = 30.0,
                   seed: int = 42) -> _Gen:
        return _Gen(lambda r: round(abs(float(r.normal(mean, sigma))), 2),
                    seed)

    @staticmethod
    def percents(seed: int = 42) -> _Gen:
        return _Gen(lambda r: float(r.uniform(0.0, 100.0)), seed)


class RandomIntegral:
    @staticmethod
    def integrals(low: int = 0, high: int = 100, seed: int = 42) -> _Gen:
        return _Gen(lambda r: int(r.integers(low, high)), seed)

    @staticmethod
    def dates(start_ms: int = 1_500_000_000_000,
              step_ms: int = 86_400_000, seed: int = 42) -> _Gen:
        return _Gen(lambda r: int(start_ms + r.integers(0, 365) * step_ms),
                    seed)

    @staticmethod
    def datetimes(start_ms: int = 1_500_000_000_000,
                  span_ms: int = 365 * 86_400_000, seed: int = 42) -> _Gen:
        return _Gen(lambda r: int(start_ms + r.integers(0, span_ms)), seed)


class RandomBinary:
    @staticmethod
    def binaries(prob_true: float = 0.5, seed: int = 42) -> _Gen:
        return _Gen(lambda r: bool(r.uniform() < prob_true), seed)


class RandomText:
    @staticmethod
    def strings(min_len: int = 3, max_len: int = 10, seed: int = 42) -> _Gen:
        letters = np.array(list(string.ascii_lowercase))

        def sample(r):
            n = int(r.integers(min_len, max_len + 1))
            return "".join(r.choice(letters, n))

        return _Gen(sample, seed)

    @staticmethod
    def textFromDomain(domain: Sequence[str], seed: int = 42) -> _Gen:
        dom = list(domain)
        return _Gen(lambda r: dom[int(r.integers(len(dom)))], seed)

    @staticmethod
    def countries(seed: int = 42) -> _Gen:
        return RandomText.textFromDomain(_COUNTRIES, seed)

    @staticmethod
    def cities(seed: int = 42) -> _Gen:
        return RandomText.textFromDomain(_CITIES, seed)

    @staticmethod
    def states(seed: int = 42) -> _Gen:
        return RandomText.textFromDomain(_STATES, seed)

    @staticmethod
    def emails(seed: int = 42) -> _Gen:
        def sample(r):
            name = "".join(r.choice(list("abcdefgh"), 6))
            return f"{name}@{_EMAILS[int(r.integers(len(_EMAILS)))]}"
        return _Gen(sample, seed)

    @staticmethod
    def phones(seed: int = 42) -> _Gen:
        return _Gen(lambda r: "+1" + "".join(
            str(int(x)) for x in r.integers(0, 10, 10)), seed)

    @staticmethod
    def picklists(domain: Sequence[str], seed: int = 42) -> _Gen:
        return RandomText.textFromDomain(domain, seed)

    @staticmethod
    def ids(length: int = 12, seed: int = 42) -> _Gen:
        alphabet = np.array(list(string.ascii_uppercase + string.digits))
        return _Gen(lambda r: "".join(r.choice(alphabet, length)), seed)

    @staticmethod
    def urls(seed: int = 42) -> _Gen:
        def sample(r):
            host = "".join(r.choice(list("abcdefgh"), 6))
            tld = ["com", "org", "net", "dev"][int(r.integers(4))]
            proto = "https" if r.uniform() < 0.8 else "http"
            return f"{proto}://{host}.{tld}/p{int(r.integers(1000))}"
        return _Gen(sample, seed)

    @staticmethod
    def base64s(min_bytes: int = 4, max_bytes: int = 32,
                seed: int = 42) -> _Gen:
        import base64 as b64

        def sample(r):
            n = int(r.integers(min_bytes, max_bytes + 1))
            return b64.b64encode(r.bytes(n)).decode("ascii")
        return _Gen(sample, seed)

    @staticmethod
    def postalCodes(seed: int = 42) -> _Gen:
        return _Gen(lambda r: "".join(str(int(x))
                                      for x in r.integers(0, 10, 5)), seed)

    @staticmethod
    def streets(seed: int = 42) -> _Gen:
        names = ["Main", "Oak", "Pine", "Maple", "Cedar", "Elm", "Market",
                 "Mission", "Valencia", "Broadway"]
        kinds = ["St", "Ave", "Blvd", "Rd", "Ln"]

        def sample(r):
            return (f"{int(r.integers(1, 9999))} "
                    f"{names[int(r.integers(len(names)))]} "
                    f"{kinds[int(r.integers(len(kinds)))]}")
        return _Gen(sample, seed)

    @staticmethod
    def textAreas(min_words: int = 5, max_words: int = 40,
                  seed: int = 42) -> _Gen:
        words = ["the", "model", "feature", "pipeline", "data", "vector",
                 "tpu", "mesh", "sweep", "metric", "column", "row", "train",
                 "score", "label", "split", "tree", "text", "map", "hash"]

        def sample(r):
            n = int(r.integers(min_words, max_words + 1))
            return " ".join(words[int(i)]
                            for i in r.integers(0, len(words), n))
        return _Gen(sample, seed)

    @staticmethod
    def uniqueTexts(prefix: str = "item", seed: int = 42) -> _Gen:
        # unique by construction: a shuffled counter rides in the value
        counter = {"n": 0}

        def sample(r):
            counter["n"] += 1
            return f"{prefix}_{counter['n']:08d}_{int(r.integers(1 << 30))}"
        return _Gen(sample, seed)


class RandomGeolocation:
    """(lat, lon, accuracy) triples (reference RandomList.ofGeolocations /
    ofGeolocationsNear)."""

    @staticmethod
    def geolocations(seed: int = 42) -> _Gen:
        return _Gen(lambda r: [float(r.uniform(-90, 90)),
                               float(r.uniform(-180, 180)),
                               float(r.integers(1, 11))], seed)

    @staticmethod
    def near(lat: float, lon: float, radius_deg: float = 1.0,
             seed: int = 42) -> _Gen:
        return _Gen(lambda r: [float(lat + r.normal(0, radius_deg)),
                               float(lon + r.normal(0, radius_deg)),
                               float(r.integers(1, 11))], seed)


class RandomList:
    @staticmethod
    def of(elem_gen: _Gen, min_len: int = 0, max_len: int = 5,
           seed: int = 42) -> _Gen:
        def sample(r):
            n = int(r.integers(min_len, max_len + 1))
            sub = iter(elem_gen.reseed(int(r.integers(1 << 30))))
            return [v for v in (next(sub) for _ in range(n)) if v is not None]
        return _Gen(sample, seed)

    @staticmethod
    def ofTexts(min_len: int = 0, max_len: int = 5, seed: int = 42) -> _Gen:
        return RandomList.of(RandomText.strings(), min_len, max_len, seed)

    @staticmethod
    def ofDates(min_len: int = 0, max_len: int = 5, seed: int = 42) -> _Gen:
        return RandomList.of(RandomIntegral.dates(), min_len, max_len, seed)

    @staticmethod
    def ofDateTimes(min_len: int = 0, max_len: int = 5,
                    seed: int = 42) -> _Gen:
        return RandomList.of(RandomIntegral.datetimes(), min_len, max_len,
                             seed)

    @staticmethod
    def ofGeolocations(seed: int = 42) -> _Gen:
        return RandomGeolocation.geolocations(seed)


class RandomSet:
    @staticmethod
    def of(domain: Sequence[str], max_len: int = 3, seed: int = 42) -> _Gen:
        return RandomMultiPickList.of(domain, max_len, seed)


class RandomMultiPickList:
    @staticmethod
    def of(domain: Sequence[str], max_len: int = 3, seed: int = 42) -> _Gen:
        dom = list(domain)

        def sample(r):
            n = int(r.integers(0, max_len + 1))
            return set(r.choice(dom, size=min(n, len(dom)), replace=False))
        return _Gen(sample, seed)


class RandomMap:
    @staticmethod
    def of(value_gen: _Gen, keys: Sequence[str], seed: int = 42,
           prob_key: float = 0.8) -> _Gen:
        ks = list(keys)

        def sample(r):
            sub = iter(value_gen.reseed(int(r.integers(1 << 30))))
            out = {}
            for k in ks:
                if r.uniform() < prob_key:
                    v = next(sub)
                    if v is not None:
                        out[k] = v
            return out
        return _Gen(sample, seed)

    # typed helpers mirroring the reference's RandomMap.of* constructors
    @staticmethod
    def ofReals(keys: Sequence[str], seed: int = 42) -> _Gen:
        return RandomMap.of(RandomReal.normal(), keys, seed)

    @staticmethod
    def ofTexts(keys: Sequence[str], seed: int = 42) -> _Gen:
        return RandomMap.of(RandomText.strings(), keys, seed)

    @staticmethod
    def ofBinaries(keys: Sequence[str], seed: int = 42) -> _Gen:
        return RandomMap.of(RandomBinary.binaries(), keys, seed)

    @staticmethod
    def ofIntegrals(keys: Sequence[str], seed: int = 42) -> _Gen:
        return RandomMap.of(RandomIntegral.integrals(), keys, seed)

    @staticmethod
    def ofDates(keys: Sequence[str], seed: int = 42) -> _Gen:
        return RandomMap.of(RandomIntegral.dates(), keys, seed)

    @staticmethod
    def ofGeolocations(keys: Sequence[str], seed: int = 42) -> _Gen:
        return RandomMap.of(RandomGeolocation.geolocations(), keys, seed)

    @staticmethod
    def ofMultiPickLists(keys: Sequence[str], domain: Sequence[str],
                         seed: int = 42) -> _Gen:
        return RandomMap.of(RandomMultiPickList.of(domain), keys, seed)


class RandomVector:
    @staticmethod
    def dense(dim: int, seed: int = 42) -> _Gen:
        return _Gen(lambda r: r.normal(size=dim).astype(np.float32), seed)

    @staticmethod
    def sparse(dim: int, density: float = 0.1, seed: int = 42) -> _Gen:
        def sample(r):
            v = r.normal(size=dim).astype(np.float32)
            return np.where(r.uniform(size=dim) < density, v,
                            np.float32(0.0))
        return _Gen(sample, seed)

    @staticmethod
    def binary(dim: int, prob_one: float = 0.5, seed: int = 42) -> _Gen:
        return _Gen(lambda r: (r.uniform(size=dim) < prob_one
                               ).astype(np.float32), seed)

    @staticmethod
    def ones(dim: int, seed: int = 42) -> _Gen:
        return _Gen(lambda r: np.ones(dim, np.float32), seed)

    @staticmethod
    def zeros(dim: int, seed: int = 42) -> _Gen:
        return _Gen(lambda r: np.zeros(dim, np.float32), seed)
