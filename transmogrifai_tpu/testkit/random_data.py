"""Seeded random feature-data generators.

Parity: reference ``testkit/src/main/scala/com/salesforce/op/testkit/
Random{Text,Real,Integral,Binary,List,Map,Set,Vector}.scala`` — infinite
deterministic generators per feature type with a probability of empty,
``.limit(n)`` to materialize.
"""

from __future__ import annotations

import string
from typing import Any, Callable, Iterator, Optional, Sequence

import numpy as np

__all__ = ["RandomReal", "RandomIntegral", "RandomBinary", "RandomText",
           "RandomList", "RandomMultiPickList", "RandomMap", "RandomVector"]

_COUNTRIES = ["USA", "Canada", "Mexico", "Brazil", "France", "Germany",
              "Japan", "India", "China", "Australia", "Kenya", "Egypt"]
_CITIES = ["San Francisco", "New York", "Paris", "Berlin", "Tokyo", "Delhi",
           "Shanghai", "Sydney", "Nairobi", "Cairo", "Toronto", "Recife"]
_STATES = ["CA", "NY", "TX", "WA", "OR", "NV", "AZ", "CO", "IL", "MA"]
_EMAILS = ["example.com", "corp.org", "mail.net", "io.dev"]


class _Gen:
    """Infinite seeded generator with probability-of-empty."""

    def __init__(self, sample: Callable[[np.random.Generator], Any],
                 seed: int = 42, prob_empty: float = 0.0):
        self._sample = sample
        self._seed = seed
        self.prob_empty = prob_empty

    def with_prob_of_empty(self, p: float) -> "_Gen":
        return _Gen(self._sample, self._seed, p)

    def reseed(self, seed: int) -> "_Gen":
        return _Gen(self._sample, seed, self.prob_empty)

    def __iter__(self) -> Iterator[Any]:
        rng = np.random.default_rng(self._seed)
        while True:
            if self.prob_empty > 0 and rng.uniform() < self.prob_empty:
                yield None
            else:
                yield self._sample(rng)

    def limit(self, n: int) -> list:
        it = iter(self)
        return [next(it) for _ in range(n)]


class RandomReal:
    @staticmethod
    def normal(mean: float = 0.0, sigma: float = 1.0, seed: int = 42) -> _Gen:
        return _Gen(lambda r: float(r.normal(mean, sigma)), seed)

    @staticmethod
    def uniform(low: float = 0.0, high: float = 1.0, seed: int = 42) -> _Gen:
        return _Gen(lambda r: float(r.uniform(low, high)), seed)

    @staticmethod
    def poisson(lam: float = 1.0, seed: int = 42) -> _Gen:
        return _Gen(lambda r: float(r.poisson(lam)), seed)

    @staticmethod
    def logNormal(mean: float = 0.0, sigma: float = 1.0, seed: int = 42) -> _Gen:
        return _Gen(lambda r: float(r.lognormal(mean, sigma)), seed)


class RandomIntegral:
    @staticmethod
    def integrals(low: int = 0, high: int = 100, seed: int = 42) -> _Gen:
        return _Gen(lambda r: int(r.integers(low, high)), seed)

    @staticmethod
    def dates(start_ms: int = 1_500_000_000_000,
              step_ms: int = 86_400_000, seed: int = 42) -> _Gen:
        return _Gen(lambda r: int(start_ms + r.integers(0, 365) * step_ms),
                    seed)


class RandomBinary:
    @staticmethod
    def binaries(prob_true: float = 0.5, seed: int = 42) -> _Gen:
        return _Gen(lambda r: bool(r.uniform() < prob_true), seed)


class RandomText:
    @staticmethod
    def strings(min_len: int = 3, max_len: int = 10, seed: int = 42) -> _Gen:
        letters = np.array(list(string.ascii_lowercase))

        def sample(r):
            n = int(r.integers(min_len, max_len + 1))
            return "".join(r.choice(letters, n))

        return _Gen(sample, seed)

    @staticmethod
    def textFromDomain(domain: Sequence[str], seed: int = 42) -> _Gen:
        dom = list(domain)
        return _Gen(lambda r: dom[int(r.integers(len(dom)))], seed)

    @staticmethod
    def countries(seed: int = 42) -> _Gen:
        return RandomText.textFromDomain(_COUNTRIES, seed)

    @staticmethod
    def cities(seed: int = 42) -> _Gen:
        return RandomText.textFromDomain(_CITIES, seed)

    @staticmethod
    def states(seed: int = 42) -> _Gen:
        return RandomText.textFromDomain(_STATES, seed)

    @staticmethod
    def emails(seed: int = 42) -> _Gen:
        def sample(r):
            name = "".join(r.choice(list("abcdefgh"), 6))
            return f"{name}@{_EMAILS[int(r.integers(len(_EMAILS)))]}"
        return _Gen(sample, seed)

    @staticmethod
    def phones(seed: int = 42) -> _Gen:
        return _Gen(lambda r: "+1" + "".join(
            str(int(x)) for x in r.integers(0, 10, 10)), seed)

    @staticmethod
    def picklists(domain: Sequence[str], seed: int = 42) -> _Gen:
        return RandomText.textFromDomain(domain, seed)


class RandomList:
    @staticmethod
    def of(elem_gen: _Gen, min_len: int = 0, max_len: int = 5,
           seed: int = 42) -> _Gen:
        def sample(r):
            n = int(r.integers(min_len, max_len + 1))
            sub = iter(elem_gen.reseed(int(r.integers(1 << 30))))
            return [v for v in (next(sub) for _ in range(n)) if v is not None]
        return _Gen(sample, seed)


class RandomMultiPickList:
    @staticmethod
    def of(domain: Sequence[str], max_len: int = 3, seed: int = 42) -> _Gen:
        dom = list(domain)

        def sample(r):
            n = int(r.integers(0, max_len + 1))
            return set(r.choice(dom, size=min(n, len(dom)), replace=False))
        return _Gen(sample, seed)


class RandomMap:
    @staticmethod
    def of(value_gen: _Gen, keys: Sequence[str], seed: int = 42) -> _Gen:
        ks = list(keys)

        def sample(r):
            sub = iter(value_gen.reseed(int(r.integers(1 << 30))))
            out = {}
            for k in ks:
                if r.uniform() < 0.8:
                    v = next(sub)
                    if v is not None:
                        out[k] = v
            return out
        return _Gen(sample, seed)


class RandomVector:
    @staticmethod
    def dense(dim: int, seed: int = 42) -> _Gen:
        return _Gen(lambda r: r.normal(size=dim).astype(np.float32), seed)
