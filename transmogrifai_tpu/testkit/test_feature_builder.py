"""TestFeatureBuilder: build (features, HostFrame) from raw values.

Parity: reference ``testkit/.../TestFeatureBuilder.scala:1-416`` — the
canonical way test suites conjure a frame plus typed features from tuples.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

from transmogrifai_tpu.features.builder import FeatureBuilder
from transmogrifai_tpu.features.feature import Feature
from transmogrifai_tpu.frame import HostColumn, HostFrame
from transmogrifai_tpu.types import feature_types as ft

__all__ = ["TestFeatureBuilder"]


class TestFeatureBuilder:
    @staticmethod
    def build(*columns: tuple, response: Optional[str] = None
              ) -> tuple[dict[str, Feature], HostFrame]:
        """``build(("age", ft.Real, [1.0, None]), ...)`` ->
        ({name: Feature}, HostFrame)."""
        cols = {}
        for name, ftype, values in columns:
            cols[name] = HostColumn.from_values(ftype, list(values))
        frame = HostFrame(cols)
        feats = FeatureBuilder.from_frame(frame, response=response)
        return feats, frame

    @staticmethod
    def from_generators(n: int, response: Optional[str] = None,
                        **gens) -> tuple[dict[str, Feature], HostFrame]:
        """``from_generators(100, age=(ft.Real, RandomReal.normal()), ...)``"""
        columns = [(name, ftype, gen.limit(n))
                   for name, (ftype, gen) in gens.items()]
        return TestFeatureBuilder.build(*columns, response=response)
