"""Columnar data: host frame (numpy) and device columns (JAX pytrees).

This replaces the reference's Spark DataFrame/RDD data abstraction
(`features/.../utils/spark/RichDataset.scala`, `readers/DataReader.scala`)
with a TPU-first design:

- **HostFrame**: immutable dict of named ``HostColumn``s (numpy-backed).
  Strings and maps live here; categorical columns can be dictionary-encoded.
  This is the analog of the raw DataFrame produced by the readers.
- **Device columns**: fixed-shape arrays + validity masks registered as JAX
  pytrees (``NumericColumn``, ``CodesColumn``, ``VectorColumn``). Nullability
  is a mask, not an Option. These flow through jitted, mesh-sharded stage
  programs; the row (batch) axis shards over the ``"data"`` mesh axis.

There is no shuffle: grouped aggregation is host-side sort + device segment
ops (see readers.aggregate).
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Any, Iterable, Mapping, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from transmogrifai_tpu.types import feature_types as ft

__all__ = [
    "HostColumn", "HostFrame", "NumericColumn", "CodesColumn", "VectorColumn",
    "DeviceFrame", "NUMERIC_KINDS", "TEXT_KINDS", "MAP_KINDS", "LIST_KINDS",
    "frame_fingerprint", "device_col_nbytes",
]

# device_kind families
NUMERIC_KINDS = frozenset({"real", "integral", "binary", "date", "datetime"})
TEXT_KINDS = frozenset({
    "text", "textarea", "email", "url", "phone", "id", "picklist", "combobox",
    "base64", "country", "state", "city", "postalcode", "street",
})
LIST_KINDS = frozenset({"textlist", "datelist", "datetimelist"})
MAP_KINDS = frozenset({k for k in (
    "map_text map_textarea map_email map_url map_phone map_id map_picklist "
    "map_combobox map_base64 map_country map_state map_city map_postalcode "
    "map_street map_real map_currency map_percent map_integral map_date "
    "map_datetime map_binary map_multipicklist map_geolocation map_namestats "
    "prediction").split()})


def _kind_of(ftype: type[ft.FeatureType]) -> str:
    return ftype.device_kind


# ---------------------------------------------------------------------------
# Host columns
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class HostColumn:
    """One feature column on host.

    Representation by kind family:
      numerics      -> float64 ``values`` + bool ``mask`` (True = present)
      text          -> object ndarray of ``str | None`` in ``values``
      lists/sets    -> object ndarray of list/set in ``values``
      geolocation   -> float64 (n, 3) ``values`` + bool ``mask``
      vector        -> float32 (n, d) ``values``
      maps          -> object ndarray of dict in ``values``
    """

    ftype: type[ft.FeatureType]
    values: np.ndarray
    mask: Optional[np.ndarray] = None  # bool[n]; None for kinds w/o mask
    meta: Optional[Any] = None         # VectorMetadata for vector kinds

    @property
    def kind(self) -> str:
        return _kind_of(self.ftype)

    def __len__(self) -> int:
        return int(self.values.shape[0])

    # -- construction -------------------------------------------------------
    @staticmethod
    def builder(ftype: type[ft.FeatureType]):
        """Resolve the kind dispatch ONCE and return a chunk builder
        ``(raw values) -> HostColumn``. Chunked/streaming ingest calls
        this per reader, not per micro-batch: the per-column schema
        resolution (kind family, representation choice) used to re-run
        on every chunk concat (``readers/base.generate_frame``), which a
        high-frequency micro-batch stream paid per batch."""
        kind = _kind_of(ftype)
        if kind in NUMERIC_KINDS:
            return lambda raw: HostColumn._build_numeric(ftype, raw)
        if kind in TEXT_KINDS:
            return lambda raw: HostColumn._build_text(ftype, raw)
        if kind == "geolocation":
            return lambda raw: HostColumn._build_geolocation(ftype, raw)
        if kind == "vector":
            return lambda raw: HostColumn._build_vector(ftype, raw)
        return lambda raw: HostColumn._build_object(ftype, raw)

    @staticmethod
    def from_values(ftype: type[ft.FeatureType], raw: Sequence[Any]) -> "HostColumn":
        """Build a column from python values (None = missing), validating via
        the feature type (the columnar analog of wrapping each value)."""
        return HostColumn.builder(ftype)(raw)

    @staticmethod
    def _build_numeric(ftype: type[ft.FeatureType], raw: Sequence[Any]) -> "HostColumn":
        n = len(raw)
        vals = np.zeros(n, dtype=np.float64)
        mask = np.zeros(n, dtype=bool)
        for i, v in enumerate(raw):
            pv = ftype._validate(v)
            if pv is not None:
                vals[i] = float(pv)
                mask[i] = True
        if not ftype.is_nullable and not mask.all():
            raise ft.FeatureTypeValueError(
                f"{ftype.__name__} column contains empty values")
        return HostColumn(ftype, vals, mask)

    @staticmethod
    def _build_text(ftype: type[ft.FeatureType], raw: Sequence[Any]) -> "HostColumn":
        vals = np.empty(len(raw), dtype=object)
        for i, v in enumerate(raw):
            vals[i] = ftype._validate(v)
        return HostColumn(ftype, vals, None)

    @staticmethod
    def _build_geolocation(ftype: type[ft.FeatureType], raw: Sequence[Any]) -> "HostColumn":
        n = len(raw)
        vals = np.zeros((n, 3), dtype=np.float64)
        mask = np.zeros(n, dtype=bool)
        for i, v in enumerate(raw):
            pv = ftype._validate(v)
            if pv:
                vals[i] = pv
                mask[i] = True
        return HostColumn(ftype, vals, mask)

    @staticmethod
    def _build_vector(ftype: type[ft.FeatureType], raw: Sequence[Any]) -> "HostColumn":
        n = len(raw)
        arrs = [np.asarray(ftype._validate(v), dtype=np.float32) for v in raw]
        d = max((a.shape[0] for a in arrs), default=0)
        vals = np.zeros((n, d), dtype=np.float32)
        for i, a in enumerate(arrs):
            if a.shape[0] not in (0, d):
                raise ft.FeatureTypeValueError(
                    f"ragged vector column: {a.shape[0]} vs {d}")
            if a.shape[0] == d:
                vals[i] = a
        return HostColumn(ftype, vals, None)

    @staticmethod
    def _build_object(ftype: type[ft.FeatureType], raw: Sequence[Any]) -> "HostColumn":
        # lists, sets, maps, prediction -> object array of validated values
        vals = np.empty(len(raw), dtype=object)
        for i, v in enumerate(raw):
            vals[i] = ftype._validate(v)
        return HostColumn(ftype, vals, None)

    # -- access -------------------------------------------------------------
    def python_value(self, i: int) -> Any:
        """Row value as the feature type's python value (None when missing)."""
        kind = self.kind
        if kind in NUMERIC_KINDS:
            if not self.mask[i]:
                return None
            v = self.values[i]
            if kind in ("integral", "date", "datetime"):
                return int(v)
            if kind == "binary":
                return bool(v)
            return float(v)
        if kind == "geolocation":
            return list(self.values[i]) if self.mask[i] else []
        if kind == "vector":
            return np.asarray(self.values[i])
        return self.values[i]

    def take(self, idx: np.ndarray) -> "HostColumn":
        return HostColumn(
            self.ftype,
            self.values[idx],
            None if self.mask is None else self.mask[idx],
            self.meta,
        )

    @staticmethod
    def concat(chunks: Sequence["HostColumn"]) -> "HostColumn":
        """Row-concatenate same-typed column chunks (the chunked-ingest
        combiner). Vector chunks may differ in width (per-chunk max): the
        result pads to the overall max."""
        if not chunks:
            raise ValueError("concat of zero chunks")
        first = chunks[0]
        if len(chunks) == 1:
            return first
        if first.kind == "vector":
            widths = {int(c.values.shape[1]) for c in chunks}
            d = max(widths)
            # chunks may legitimately be NARROWER only when entirely empty
            # (width 0: every row was an empty vector); two different
            # non-zero widths are the same ragged-column error from_values
            # raises on unchunked data
            if len(widths - {0, d}) > 0:
                raise ft.FeatureTypeValueError(
                    f"ragged vector column across chunks: widths {sorted(widths)}")
            n = sum(len(c) for c in chunks)
            vals = np.zeros((n, d), np.float32)
            at = 0
            for c in chunks:
                vals[at:at + len(c), :c.values.shape[1]] = c.values
                at += len(c)
            meta = next((c.meta for c in chunks if c.meta is not None), None)
            return HostColumn(first.ftype, vals, None, meta)
        values = np.concatenate([c.values for c in chunks])
        mask = (np.concatenate([c.mask for c in chunks])
                if first.mask is not None else None)
        return HostColumn(first.ftype, values, mask, first.meta)


# ---------------------------------------------------------------------------
# Device columns (JAX pytrees)
# ---------------------------------------------------------------------------

@jax.tree_util.register_pytree_node_class
@dataclass(frozen=True)
class NumericColumn:
    """float32 values + float32 {0,1} mask. Missing slots hold 0 in values."""

    values: jax.Array  # f32[n]
    mask: jax.Array    # f32[n]

    def tree_flatten(self):
        return (self.values, self.mask), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    @staticmethod
    def from_host(col: HostColumn) -> "NumericColumn":
        return NumericColumn(
            jnp.asarray(np.where(col.mask, col.values, 0.0), dtype=jnp.float32),
            jnp.asarray(col.mask, dtype=jnp.float32),
        )


@jax.tree_util.register_pytree_node_class
@dataclass(frozen=True)
class CodesColumn:
    """Dictionary-encoded categorical: int32 codes into ``vocab``; -1 = null.

    The vocab is static aux data (affects compiled shapes only via downstream
    one-hot sizes, which are fixed at fit time).
    """

    codes: jax.Array            # i32[n]
    vocab: tuple[str, ...]      # aux (host-side)

    def tree_flatten(self):
        return (self.codes,), self.vocab

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], aux)


@jax.tree_util.register_pytree_node_class
@dataclass(frozen=True)
class VectorColumn:
    """Dense f32[n, d] feature-vector block with provenance metadata.

    The metadata (see ``transmogrifai_tpu.vector_metadata``) is aux data: it
    names every one of the d columns with its parent feature, grouping,
    pivot/indicator value and null-indicator flag — the backbone of
    SanityChecker, ModelInsights and LOCO, mirroring the reference's
    ``OpVectorMetadata`` riding on DataFrame schema.
    """

    values: jax.Array  # f32[n, d]
    metadata: Any = None  # VectorMetadata | None (aux, static)

    def tree_flatten(self):
        return (self.values,), self.metadata

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], aux)

    @property
    def width(self) -> int:
        return int(self.values.shape[-1])


@jax.tree_util.register_pytree_node_class
@dataclass(frozen=True)
class PredictionColumn:
    """Model output: prediction f32[n], raw scores f32[n,C], probabilities
    f32[n,C] — the columnar analog of the reference's ``Prediction`` map
    type (prediction/rawPrediction/probability keys)."""

    prediction: jax.Array
    raw_prediction: jax.Array
    probability: jax.Array

    def tree_flatten(self):
        return (self.prediction, self.raw_prediction, self.probability), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    def pos_score(self) -> jax.Array:
        """Positive-class score: P(class=1) when a real probability matrix is
        present, else the raw prediction. The single guard for the (n,0)
        empty-probability convention used by margin-only/regression models."""
        prob = self.probability
        if prob is not None and getattr(prob, "ndim", 1) == 2 and prob.shape[1] >= 2:
            return jnp.asarray(prob[:, 1], jnp.float32)
        return jnp.asarray(self.prediction, jnp.float32)


DeviceColumn = Any  # NumericColumn | CodesColumn | VectorColumn | PredictionColumn
DeviceFrame = dict  # dict[str, DeviceColumn]


# ---------------------------------------------------------------------------
# Host frame
# ---------------------------------------------------------------------------

class HostFrame:
    """Immutable named collection of equal-length HostColumns.

    The analog of the raw/intermediate Spark DataFrame. Cheap structural
    sharing: with_columns/select return new frames referencing the same
    column objects.
    """

    def __init__(self, columns: Mapping[str, HostColumn], key: Optional[np.ndarray] = None):
        lens = {len(c) for c in columns.values()}
        if len(lens) > 1:
            raise ValueError(f"ragged frame: column lengths {lens}")
        self._cols = dict(columns)
        self._n = lens.pop() if lens else 0
        self.key = key  # optional entity-key column (object ndarray of str)

    # -- construction -------------------------------------------------------
    @staticmethod
    def from_dict(data: Mapping[str, tuple[type[ft.FeatureType], Sequence[Any]]],
                  key: Optional[Sequence[str]] = None) -> "HostFrame":
        cols = {name: HostColumn.from_values(t, vals) for name, (t, vals) in data.items()}
        k = None if key is None else np.asarray(list(key), dtype=object)
        return HostFrame(cols, k)

    # -- structure ----------------------------------------------------------
    @property
    def n_rows(self) -> int:
        return self._n

    @property
    def columns(self) -> dict[str, HostColumn]:
        return dict(self._cols)

    def __contains__(self, name: str) -> bool:
        return name in self._cols

    def __getitem__(self, name: str) -> HostColumn:
        return self._cols[name]

    def names(self) -> list[str]:
        return list(self._cols)

    def with_columns(self, new: Mapping[str, HostColumn]) -> "HostFrame":
        cols = dict(self._cols)
        cols.update(new)
        return HostFrame(cols, self.key)

    def select(self, names: Iterable[str]) -> "HostFrame":
        return HostFrame({n: self._cols[n] for n in names}, self.key)

    def drop(self, names: Iterable[str]) -> "HostFrame":
        names = set(names)
        return HostFrame({n: c for n, c in self._cols.items() if n not in names},
                         self.key)

    def take(self, idx: np.ndarray) -> "HostFrame":
        return HostFrame({n: c.take(idx) for n, c in self._cols.items()},
                         None if self.key is None else self.key[idx])

    def row(self, i: int) -> dict[str, Any]:
        return {n: c.python_value(i) for n, c in self._cols.items()}

    def iter_rows(self):
        for i in range(self._n):
            yield self.row(i)

    def __repr__(self) -> str:
        cols = ", ".join(f"{n}: {c.ftype.__name__}" for n, c in self._cols.items())
        return f"HostFrame(n={self._n}, [{cols}])"


# ---------------------------------------------------------------------------
# Identity + accounting helpers (round 14: device-frame cache)
# ---------------------------------------------------------------------------

def frame_fingerprint(frame: "HostFrame") -> str:
    """Content fingerprint of a host frame: column names, feature types,
    and the FULL value/mask bytes (blake2b). This keys the device-frame
    cache, so it must be collision-safe in practice — numeric columns hash
    at memory bandwidth; object columns (strings/maps) hash per-row reprs,
    the same order of work dict-encoding them costs. Two frames with equal
    fingerprints produce identical device columns."""
    import hashlib
    h = hashlib.blake2b(digest_size=16)
    for name in sorted(frame.names()):
        col = frame[name]
        h.update(name.encode())
        h.update(col.ftype.__name__.encode())
        v = col.values
        h.update(str(v.shape).encode())
        if v.dtype == object:
            for x in v:
                h.update(repr(x).encode())
                h.update(b"\x1f")
        else:
            h.update(np.ascontiguousarray(v).tobytes())
        if col.mask is not None:
            h.update(np.ascontiguousarray(col.mask).tobytes())
        if col.meta is not None:
            # vector provenance metadata distinguishes otherwise
            # value-equal frames (it rides the cached device column)
            h.update(repr(col.meta).encode())
    if frame.key is not None:
        for k in frame.key:
            h.update(str(k).encode())
            h.update(b"\x1f")
    return h.hexdigest()


def device_col_nbytes(col: Any) -> int:
    """Approximate HBM bytes a device column holds (leaf array nbytes);
    the device-frame cache's budget accounting."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(col):
        total += int(getattr(leaf, "nbytes", 0) or 0)
    return total
