"""Native (C++) components, built on demand with g++ and bound via ctypes.

The reference's JVM-external native layer (netlib BLAS, libxgboost JNI —
SURVEY §2.8) maps here: host-side runtime pieces that don't belong on the
TPU compute path get real native implementations, compiled once into
``_build/`` next to this file and loaded with ctypes. Every binding must
keep a pure-Python fallback so the framework works where no toolchain
exists.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_BUILD_LOCK = threading.Lock()
_DIR = os.path.dirname(os.path.abspath(__file__))
_BUILD_DIR = os.path.join(_DIR, "_build")


def build_and_load(source_name: str, lib_name: str) -> Optional[ctypes.CDLL]:
    """Compile ``source_name`` (in this dir) to ``_build/lib<name>.so`` if
    stale/missing and dlopen it. Returns None when compilation fails (no
    toolchain, sandbox, ...) — callers fall back to Python."""
    src = os.path.join(_DIR, source_name)
    out = os.path.join(_BUILD_DIR, f"lib{lib_name}.so")
    with _BUILD_LOCK:
        try:
            if (not os.path.exists(out)
                    or os.path.getmtime(out) < os.path.getmtime(src)):
                os.makedirs(_BUILD_DIR, exist_ok=True)
                subprocess.run(
                    ["g++", "-O3", "-std=c++17", "-shared", "-fPIC",
                     "-o", out, src],
                    check=True, capture_output=True, timeout=120)
            return ctypes.CDLL(out)
        except Exception:  # failure-ok: native lib is optional; numpy fallback
            return None
