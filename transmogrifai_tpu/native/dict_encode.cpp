// Native dictionary encoder for text -> codes column ingest.
//
// PipelineData dictionary-encodes categorical text columns on first device
// use; the Python path (sorted-vocab build + per-row dict lookups) crawls
// on Criteo-scale categorical columns. This is the host-side hot loop as
// one C pass: open-addressing FNV-1a hash over the row byte-slices,
// assigning first-seen ids and remembering one representative row per
// unique value. Python then sorts the (few) unique values and remaps the
// codes vectorized — the heavy O(n) work never touches the interpreter.
//
// Parity contract: codes must equal the Python `sorted(vocab).index(v)`
// encoding exactly (pipeline_data._encode_text); the Python caller does the
// sort + remap, so this file only needs first-seen ids to be consistent.

#include <cstdint>
#include <cstring>

namespace {

inline uint64_t fnv1a(const char* p, int64_t len) {
    uint64_t h = 1469598103934665603ull;
    for (int64_t i = 0; i < len; ++i) {
        h ^= (unsigned char)p[i];
        h *= 1099511628211ull;
    }
    return h;
}

}  // namespace

extern "C" {

// Encode n fixed-width rows (buf is an [n, width] zero-padded bytes matrix
// — numpy's 'S{width}' layout, so the caller builds it with ONE vectorized
// astype, no per-row Python; nulls[r] != 0 marks missing -> code -1).
// Writes first-seen-id codes to codes_out and the representative row of
// each unique id to rep_rows_out (capacity max_uniques). Returns the
// number of uniques, or -1 when max_uniques would be exceeded (caller
// falls back to the sort path).
int64_t dict_encode(const char* buf, int64_t width,
                    const unsigned char* nulls, int64_t n,
                    int32_t* codes_out, int64_t* rep_rows_out,
                    int64_t max_uniques) {
    // open addressing, power-of-two table >= 2*max_uniques
    int64_t cap = 16;
    while (cap < max_uniques * 2) cap <<= 1;
    int64_t* table = new int64_t[cap];  // unique id + 1; 0 = empty
    std::memset(table, 0, sizeof(int64_t) * cap);
    const uint64_t mask = (uint64_t)cap - 1;

    int64_t n_unique = 0;
    for (int64_t r = 0; r < n; ++r) {
        if (nulls[r]) {
            codes_out[r] = -1;
            continue;
        }
        const char* p = buf + r * width;
        uint64_t slot = fnv1a(p, width) & mask;
        for (;;) {
            int64_t entry = table[slot];
            if (entry == 0) {  // new value
                if (n_unique >= max_uniques) {
                    delete[] table;
                    return -1;
                }
                rep_rows_out[n_unique] = r;
                table[slot] = n_unique + 1;
                codes_out[r] = (int32_t)n_unique;
                ++n_unique;
                break;
            }
            const int64_t id = entry - 1;
            if (std::memcmp(buf + rep_rows_out[id] * width, p,
                            (size_t)width) == 0) {
                codes_out[r] = (int32_t)id;
                break;
            }
            slot = (slot + 1) & mask;
        }
    }
    delete[] table;
    return n_unique;
}

}  // extern "C"
