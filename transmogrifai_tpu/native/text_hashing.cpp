// Native tokenizer + hashing-trick accumulator for the text vectorizer.
//
// The reference leans on Lucene (JVM) for tokenization and Spark's murmur3
// HashingTF for the hashing trick (OPCollectionHashingVectorizer.scala); our
// host-side equivalent tokenizes ASCII word runs and hashes with zlib's
// CRC-32 — bit-identical to Python's zlib.crc32, so the Python row path and
// this columnar path agree exactly (the OpTransformerSpec parity contract).
// Non-ASCII columns stay on the Python/regex path (dispatch in hashing.py).

#include <cstdint>
#include <cstring>

namespace {

uint32_t crc_table[256];
bool crc_ready = false;

void init_crc() {
    if (crc_ready) return;
    for (uint32_t i = 0; i < 256; ++i) {
        uint32_t c = i;
        for (int k = 0; k < 8; ++k)
            c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
        crc_table[i] = c;
    }
    crc_ready = true;
}

inline uint32_t crc32_update(uint32_t crc, const unsigned char* p,
                             int64_t len) {
    crc ^= 0xFFFFFFFFu;
    for (int64_t i = 0; i < len; ++i)
        crc = crc_table[(crc ^ p[i]) & 0xFF] ^ (crc >> 8);
    return crc ^ 0xFFFFFFFFu;
}

inline bool is_word(unsigned char c) {
    return (c >= '0' && c <= '9') || (c >= 'a' && c <= 'z') ||
           (c >= 'A' && c <= 'Z');
}

// The ONE tokenizer loop: every entry point routes through this so the
// word-character set, lowercase rule, and 4096-byte token cap cannot drift
// between consumers. emit(row, crc) fires once per token.
template <class Emit>
inline void scan_tokens(const char* buf, const int64_t* offsets, int64_t n,
                        int32_t lowercase, Emit&& emit) {
    init_crc();
    unsigned char tok[4096];
    for (int64_t r = 0; r < n; ++r) {
        const char* p = buf + offsets[r];
        const int64_t len = offsets[r + 1] - offsets[r];
        int64_t t = 0;
        for (int64_t i = 0; i <= len; ++i) {
            unsigned char c = (i < len) ? (unsigned char)p[i] : 0;
            if (i < len && is_word(c)) {
                if (t < (int64_t)sizeof(tok))
                    tok[t++] = lowercase && c >= 'A' && c <= 'Z'
                                   ? c + 32 : c;
            } else if (t > 0) {
                emit(r, crc32_update(0u, tok, t));
                t = 0;
            }
        }
    }
}

}  // namespace

extern "C" {

// buf: concatenated UTF-8 rows; offsets: [n+1] byte offsets into buf.
// out: float32 [n, stride] row-major; token bins accumulate into
// out[r, col_offset + crc32(token) % num_bins].
void hash_tokens_batch(const char* buf, const int64_t* offsets, int64_t n,
                       int32_t num_bins, int32_t lowercase,
                       int32_t binary_freq, float* out, int64_t stride,
                       int64_t col_offset) {
    scan_tokens(buf, offsets, n, lowercase,
                [&](int64_t r, uint32_t h) {
                    float* row = out + r * stride + col_offset;
                    int64_t b = (int64_t)(h % (uint32_t)num_bins);
                    if (binary_freq) row[b] = 1.0f;
                    else row[b] += 1.0f;
                });
}

// Accumulates every row's token bins into ONE histogram hist[num_bins]
// (double counts) — the RawFeatureFilter distribution pass, which needs the
// corpus-level token distribution rather than per-row vectors, so no
// [n, bins] intermediate is materialized.
void hash_tokens_hist(const char* buf, const int64_t* offsets, int64_t n,
                      int32_t num_bins, int32_t lowercase, double* hist) {
    scan_tokens(buf, offsets, n, lowercase,
                [&](int64_t, uint32_t h) {
                    hist[h % (uint32_t)num_bins] += 1.0;
                });
}

}  // extern "C"
