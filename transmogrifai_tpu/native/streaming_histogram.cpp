// Streaming decision-tree histogram (Ben-Haim & Tom-Tov, JMLR 2010).
//
// Native C++ equivalent of the reference's
// utils/src/main/java/com/salesforce/op/utils/stats/StreamingHistogram.java:
// bounded-bin histogram built by spooled exact counts that collapse the two
// closest centroids once the bin budget is exceeded; mergeable across
// shards (the map-reduce combiner in the reference's RDD aggregate).
//
// Exposed as a flat C ABI for ctypes. Bins and spool live in ordered
// std::maps (matching the reference's TreeMap flush order, which affects
// which centroids merge), and the bulk path ingests a whole column per call
// so the Python boundary is crossed once per array, not once per value.

#include <cmath>
#include <cstdint>
#include <limits>
#include <map>
#include <unordered_map>
#include <vector>

namespace {

struct Histogram {
  std::map<double, int64_t> bin;
  std::map<double, int64_t> spool;
  int max_bin_size;
  int max_spool_size;
  int64_t round_seconds;

  void merge_closest() {
    while (static_cast<int>(bin.size()) > max_bin_size) {
      auto it = bin.begin();
      double p1 = it->first;
      ++it;
      double q1 = p1, q2 = it->first;
      double smallest = q2 - q1;
      double prev = it->first;
      for (++it; it != bin.end(); ++it) {
        double diff = it->first - prev;
        if (diff < smallest) {
          smallest = diff;
          q1 = prev;
          q2 = it->first;
        }
        prev = it->first;
      }
      int64_t k1 = bin[q1], k2 = bin[q2];
      bin.erase(q1);
      bin.erase(q2);
      bin[(q1 * k1 + q2 * k2) / static_cast<double>(k1 + k2)] += k1 + k2;
    }
  }

  void flush() {
    if (spool.empty()) return;
    for (const auto& kv : spool) {
      bin[kv.first] += kv.second;
      merge_closest();
    }
    spool.clear();
  }

  void update(double p, int64_t m) {
    if (round_seconds > 1) {
      int64_t lp = static_cast<int64_t>(p);
      int64_t d = lp % round_seconds;
      if (d > 0) p = static_cast<double>(lp + (round_seconds - d));
    }
    auto it = spool.find(p);
    if (it != spool.end()) {
      it->second += m;
    } else {
      spool.emplace(p, m);
    }
    if (static_cast<int>(spool.size()) > max_spool_size) flush();
  }

  // Interpolated count of points <= b (reference StreamingHistogram.sum).
  double sum(double b) const {
    auto next = bin.upper_bound(b);
    if (next == bin.end()) {
      double total = 0;
      for (const auto& kv : bin) total += static_cast<double>(kv.second);
      return total;
    }
    // floor entry: greatest key <= b
    if (next == bin.begin()) return 0.0;
    auto pi = std::prev(next);
    double ki = static_cast<double>(pi->second);
    double knext = static_cast<double>(next->second);
    double weight = (b - pi->first) / (next->first - pi->first);
    double mb = ki + (knext - ki) * weight;
    double s = (ki + mb) * weight / 2.0 + ki / 2.0;
    for (auto it = bin.begin(); it != pi; ++it)
      s += static_cast<double>(it->second);
    return s;
  }
};

}  // namespace

extern "C" {

void* shist_new(int max_bin_size, int max_spool_size, int round_seconds) {
  Histogram* h = new Histogram();
  h->max_bin_size = max_bin_size;
  h->max_spool_size = max_spool_size;
  h->round_seconds = round_seconds < 1 ? 1 : round_seconds;
  return h;
}

void shist_free(void* ptr) { delete static_cast<Histogram*>(ptr); }

void shist_update(void* ptr, double p, int64_t m) {
  static_cast<Histogram*>(ptr)->update(p, m);
}

void shist_update_bulk(void* ptr, const double* p, int64_t n) {
  Histogram* h = static_cast<Histogram*>(ptr);
  for (int64_t i = 0; i < n; ++i) h->update(p[i], 1);
}

void shist_flush(void* ptr) { static_cast<Histogram*>(ptr)->flush(); }

int shist_size(void* ptr) {
  Histogram* h = static_cast<Histogram*>(ptr);
  h->flush();
  return static_cast<int>(h->bin.size());
}

void shist_get(void* ptr, double* centers, int64_t* counts) {
  Histogram* h = static_cast<Histogram*>(ptr);
  h->flush();
  int64_t i = 0;
  for (const auto& kv : h->bin) {
    centers[i] = kv.first;
    counts[i] = kv.second;
    ++i;
  }
}

double shist_sum(void* ptr, double b) {
  Histogram* h = static_cast<Histogram*>(ptr);
  h->flush();
  return h->sum(b);
}

void shist_merge(void* ptr, void* other) {
  Histogram* h = static_cast<Histogram*>(ptr);
  Histogram* o = static_cast<Histogram*>(other);
  o->flush();
  for (const auto& kv : o->bin) h->update(kv.first, kv.second);
}

}  // extern "C"
