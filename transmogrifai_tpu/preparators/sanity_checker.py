"""SanityChecker: automated feature validation & cleaning.

Parity: reference ``core/.../stages/impl/preparators/SanityChecker.scala:
232-656`` (+ ``SanityCheckerMetadata``, ``DerivedFeatureFilterUtils``,
``MinVarianceFilter``) — a BinaryEstimator (label RealNN, features OPVector
-> cleaned OPVector) that computes per-column statistics, label
correlations, optional feature-feature correlations, and per-categorical-
group contingency stats (Cramér's V, PMI, association-rule confidence), then
**drops columns** failing: minVariance, max/min label correlation,
maxCramersV, maxRuleConfidence — with whole-feature-group removal. Emits a
``SanityCheckerSummary`` consumed by ModelInsights.

TPU-first: every statistic is one fused jitted program over the sharded
feature matrix — masked moments and label covariance are [n,d] reductions,
the feature-feature matrix is a single [d,n]x[n,d] MXU matmul, and ALL
categorical contingency tables compute at once as ``X^T @ onehot(y)``
(the reference's per-group reduceByKey collapses into one matmul). Only the
tiny [d, C] results reach the host for the drop decisions.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from transmogrifai_tpu import frame as fr
from transmogrifai_tpu.stages.base import DeviceTransformer, Estimator
from transmogrifai_tpu.types import feature_types as ft
from transmogrifai_tpu.utils.stats import contingency_stats
from transmogrifai_tpu.vector_metadata import VectorMetadata

__all__ = ["SanityChecker", "DropIndicesModel", "SanityCheckerSummary"]


@dataclass
class ColumnStats:
    name: str
    mean: float
    variance: float
    min: float
    max: float
    corr_label: float
    dropped: bool = False
    reasons: list = field(default_factory=list)


@dataclass
class SanityCheckerSummary:
    n_rows: int
    names: list
    column_stats: list            # list[ColumnStats]
    categorical_stats: dict       # group -> {"cramersV":, "maxRuleConfidence":, "supports":}
    dropped: list                 # names
    feature_corr: Optional[list] = None   # d x d matrix (when computed)

    def to_json(self) -> dict:
        return {
            "nRows": self.n_rows,
            "columnStats": [{
                "name": c.name, "mean": c.mean, "variance": c.variance,
                "min": c.min, "max": c.max, "corrLabel": c.corr_label,
                "dropped": c.dropped, "reasons": list(c.reasons),
            } for c in self.column_stats],
            "categoricalStats": self.categorical_stats,
            "dropped": list(self.dropped),
        }


@jax.jit
def _moment_stats(X, y):
    n = X.shape[0]
    mean = jnp.mean(X, axis=0)
    var = jnp.var(X, axis=0)
    xmin = jnp.min(X, axis=0)
    xmax = jnp.max(X, axis=0)
    ymean = jnp.mean(y)
    cov = jnp.mean((X - mean) * (y - ymean)[:, None], axis=0)
    ystd = jnp.sqrt(jnp.maximum(jnp.var(y), 1e-12))
    corr = cov / (jnp.sqrt(jnp.maximum(var, 1e-12)) * ystd)
    return mean, var, xmin, xmax, corr


@jax.jit
def _contingency(X, y_onehot):
    return X.T @ y_onehot


@jax.jit
def _feature_corr(X):
    n = X.shape[0]
    mean = jnp.mean(X, axis=0)
    Xc = X - mean
    sd = jnp.sqrt(jnp.maximum(jnp.mean(Xc * Xc, axis=0), 1e-12))
    Z = Xc / sd
    return (Z.T @ Z) / n


class SanityChecker(Estimator):
    """(label, features) -> cleaned features."""

    in_types = (ft.RealNN, ft.OPVector)
    out_type = ft.OPVector

    def __init__(self,
                 max_correlation: float = 0.95,
                 min_correlation: float = 0.0,
                 min_variance: float = 1e-5,
                 max_cramers_v: float = 0.95,
                 max_rule_confidence: float = 1.0,
                 min_required_rule_support: float = 0.001,
                 remove_feature_group: bool = True,
                 compute_feature_corr: bool = True,
                 max_feature_corr_width: int = 1500,
                 categorical_label_max_classes: int = 100,
                 uid: Optional[str] = None):
        self.max_correlation = max_correlation
        self.min_correlation = min_correlation
        self.min_variance = min_variance
        self.max_cramers_v = max_cramers_v
        self.max_rule_confidence = max_rule_confidence
        self.min_required_rule_support = min_required_rule_support
        self.remove_feature_group = remove_feature_group
        self.compute_feature_corr = compute_feature_corr
        self.max_feature_corr_width = max_feature_corr_width
        self.categorical_label_max_classes = categorical_label_max_classes
        super().__init__(uid=uid)

    def fit_model(self, data) -> "DropIndicesModel":
        label_name, feat_name = self.input_names
        col = data.device_col(feat_name)
        X = col.values
        meta: Optional[VectorMetadata] = col.metadata
        y = data.device_col(label_name).values
        n, d = int(X.shape[0]), int(X.shape[1])
        names = (meta.col_names() if meta is not None and meta.size == d
                 else [f"col_{j}" for j in range(d)])

        mean, var, xmin, xmax, corr = (np.asarray(a, np.float64)
                                       for a in _moment_stats(X, y))

        # categorical groups from provenance metadata
        groups: dict[str, list[int]] = {}
        if meta is not None and meta.size == d:
            for j, cm in enumerate(meta.columns):
                g = cm.feature_group()
                if g is not None and cm.indicator_value is not None:
                    groups.setdefault(g, []).append(j)

        # contingency stats per group via one matmul for all columns
        cat_stats: dict[str, dict] = {}
        y_np = np.asarray(y)
        classes = np.unique(y_np)
        if groups and classes.size <= self.categorical_label_max_classes \
                and classes.size >= 2:
            y_onehot = jnp.asarray(
                (y_np[:, None] == classes[None, :]).astype(np.float32))
            M = np.asarray(_contingency(X, y_onehot), np.float64)
            for g, idxs in groups.items():
                cs = contingency_stats(M[idxs])
                cat_stats[g] = {
                    "cramersV": cs.cramers_v,
                    "mutualInfo": cs.mutual_info,
                    "maxRuleConfidences": cs.max_rule_confidences.tolist(),
                    "supports": cs.supports.tolist(),
                }

        # ---- drop decisions -------------------------------------------------
        col_stats = [ColumnStats(names[j], mean[j], var[j], xmin[j], xmax[j],
                                 corr[j]) for j in range(d)]
        for j, c in enumerate(col_stats):
            if c.variance < self.min_variance:
                c.reasons.append("variance too low")
            acorr = abs(c.corr_label)
            if np.isfinite(acorr):
                if acorr > self.max_correlation:
                    c.reasons.append("label correlation too high (leakage)")
                elif acorr < self.min_correlation:
                    c.reasons.append("label correlation too low")
        group_dropped: set[str] = set()
        for g, idxs in groups.items():
            st = cat_stats.get(g)
            if st is None:
                continue
            if st["cramersV"] > self.max_cramers_v:
                group_dropped.add(g)
                for j in idxs:
                    col_stats[j].reasons.append("Cramér's V too high (leakage)")
            else:
                conf = np.asarray(st["maxRuleConfidences"])
                sup = np.asarray(st["supports"])
                if np.any((conf >= self.max_rule_confidence)
                          & (sup >= self.min_required_rule_support)):
                    group_dropped.add(g)
                    for j in idxs:
                        col_stats[j].reasons.append(
                            "association rule confidence too high")
        if self.remove_feature_group and meta is not None and meta.size == d:
            # a label-corr drop on any indicator removes its whole group
            for g, idxs in groups.items():
                if g in group_dropped:
                    continue
                if any("leakage" in r for j in idxs
                       for r in col_stats[j].reasons):
                    for j in idxs:
                        if not col_stats[j].reasons:
                            col_stats[j].reasons.append(
                                "feature group removed (leaky sibling)")

        keep = [j for j, c in enumerate(col_stats) if not c.reasons]
        if not keep:
            # never drop everything: keep the highest-|corr| column
            j = int(np.nanargmax(np.abs(corr)))
            col_stats[j].reasons.clear()
            keep = [j]
        for c in col_stats:
            c.dropped = bool(c.reasons)

        fcorr = None
        if self.compute_feature_corr and d <= self.max_feature_corr_width:
            fcorr = np.asarray(_feature_corr(X), np.float64).tolist()

        summary = SanityCheckerSummary(
            n_rows=n, names=names, column_stats=col_stats,
            categorical_stats=cat_stats,
            dropped=[c.name for c in col_stats if c.dropped],
            feature_corr=fcorr)
        new_meta = meta.select(keep) if meta is not None and meta.size == d \
            else None
        return DropIndicesModel(keep_indices=keep, out_meta=new_meta,
                                summary=summary)


class DropIndicesModel(DeviceTransformer):
    """Gathers the kept columns; reindexed provenance metadata rides along."""

    in_types = (ft.RealNN, ft.OPVector)
    out_type = ft.OPVector

    def __init__(self, keep_indices=(), out_meta: Optional[VectorMetadata] = None,
                 summary: Optional[SanityCheckerSummary] = None,
                 uid: Optional[str] = None):
        self.keep_indices = [int(i) for i in keep_indices]
        self.out_meta = out_meta
        self.summary = summary
        super().__init__(uid=uid)

    def runtime_input_names(self):
        return (self.input_names[1],) if len(self.input_names) == 2 \
            else self.input_names

    def device_params(self):
        return jnp.asarray(self.keep_indices, jnp.int32)

    def device_apply(self, params, col: fr.VectorColumn) -> fr.VectorColumn:
        meta = self.out_meta
        if meta is None and col.metadata is not None \
                and col.metadata.size == int(col.values.shape[1]):
            meta = col.metadata.select(self.keep_indices)
        return fr.VectorColumn(jnp.take(col.values, params, axis=1), meta)

    def transform_row(self, *values):
        vec = np.asarray(values[-1], dtype=np.float32)
        return vec[np.asarray(self.keep_indices, dtype=np.int64)]

    def config(self):
        return {
            "keep_indices": self.keep_indices,
            "out_meta": self.out_meta.to_json() if self.out_meta else None,
            "summary": self.summary.to_json() if self.summary else None,
        }

    @classmethod
    def from_config(cls, config, uid=None):
        meta = (VectorMetadata.from_json(config["out_meta"])
                if config.get("out_meta") else None)
        return cls(keep_indices=config.get("keep_indices", ()),
                   out_meta=meta, uid=uid)
