"""SanityChecker: automated feature validation & cleaning.

Parity: reference ``core/.../stages/impl/preparators/SanityChecker.scala:
232-656`` (+ ``SanityCheckerMetadata``, ``DerivedFeatureFilterUtils``,
``MinVarianceFilter``) — a BinaryEstimator (label RealNN, features OPVector
-> cleaned OPVector) that samples rows (``sampleUpperLimit``), computes
per-column statistics, label correlations (Pearson or Spearman), the
feature-feature correlation matrix, and per-categorical-group contingency
stats (Cramér's V, PMI, association-rule confidence), then **drops columns**
failing: minVariance, max/min label correlation, maxFeatureCorr (drop the
later column of a too-correlated pair, ``DerivedFeatureFilterUtils.scala:
376-380``), maxCramersV, maxRuleConfidence — with whole-feature-group
removal (text shared-hash columns protected per ``protectTextSharedHash``).
Emits a ``SanityCheckerSummary`` consumed by ModelInsights.

TPU-first: every statistic is a monoid pytree reduced over the device mesh —
masked moments ride one fused ``shard_map`` + ``psum/pmin/pmax`` program
(the analog of the reference's ``reduceByKey(_+_)`` at
``SanityChecker.scala:265-272``), the feature-feature matrix is a single
[d,n]x[n,d] MXU matmul with the feature axis shardable over the "model"
mesh axis (the O(d²) wide-feature decomposition, SURVEY §5), and ALL
categorical contingency tables compute at once as ``X^T @ onehot(y)``. Only
tiny [d]-shaped results reach the host for the drop decisions. Mesh-padded
rows carry weight 0 and contribute monoid identity.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from transmogrifai_tpu import frame as fr
from transmogrifai_tpu.parallel import mesh as pmesh
from transmogrifai_tpu.parallel.collectives import (
    mesh_reduce_stats, tree_pmax, tree_pmin, tree_psum,
)
from transmogrifai_tpu.stages.base import DeviceTransformer, Estimator
from transmogrifai_tpu.types import feature_types as ft
from transmogrifai_tpu.utils.stats import contingency_stats
from transmogrifai_tpu.vector_metadata import VectorMetadata

__all__ = ["SanityChecker", "DropIndicesModel", "SanityCheckerSummary"]

_BIG = jnp.float32(3.4e38)

#: feature types whose shared-hash columns are protected from group removal
#: (reference DerivedFeatureFilterUtils.isTextSharedHash)
_TEXTY = ("Text", "TextArea", "TextMap", "TextAreaMap")


def _is_text_shared_hash(cm) -> bool:
    return (cm.grouping is None and cm.indicator_value is None
            and any(t in _TEXTY for t in cm.parent_feature_type))


@dataclass
class ColumnStats:
    name: str
    mean: float
    variance: float
    min: float
    max: float
    corr_label: float
    dropped: bool = False
    reasons: list = field(default_factory=list)


@dataclass
class SanityCheckerSummary:
    n_rows: int
    names: list
    column_stats: list            # list[ColumnStats]
    categorical_stats: dict       # group -> {"cramersV":, "maxRuleConfidence":, "supports":}
    dropped: list                 # names
    feature_corr: Optional[list] = None   # d x d matrix (when computed)
    correlation_type: str = "pearson"
    sample_fraction: float = 1.0

    def to_json(self) -> dict:
        return {
            "nRows": self.n_rows,
            "correlationType": self.correlation_type,
            "sampleFraction": self.sample_fraction,
            "columnStats": [{
                "name": c.name, "mean": c.mean, "variance": c.variance,
                "min": c.min, "max": c.max, "corrLabel": c.corr_label,
                "dropped": c.dropped, "reasons": list(c.reasons),
            } for c in self.column_stats],
            "categoricalStats": self.categorical_stats,
            "dropped": list(self.dropped),
        }


def _local_moments(X, Xr, y, yr, m):
    """Per-shard monoid stats: sums/extrema of the raw matrix plus the
    correlation cross-moments on the (possibly rank-transformed) matrix.
    Masked rows contribute identity (0 for sums, ±inf for extrema)."""
    mm = m[:, None]
    ym = yr * m
    return {
        "cnt": jnp.sum(m),
        "sx": jnp.sum(X * mm, axis=0),
        "sx2": jnp.sum(X * X * mm, axis=0),
        "mn": jnp.min(jnp.where(mm > 0, X, _BIG), axis=0),
        "mx": jnp.max(jnp.where(mm > 0, X, -_BIG), axis=0),
        "sr": jnp.sum(Xr * mm, axis=0),
        "sr2": jnp.sum(Xr * Xr * mm, axis=0),
        "sry": jnp.sum(Xr * ym[:, None], axis=0),
        "sy": jnp.sum(ym),
        "sy2": jnp.sum(yr * ym),
    }


def _combine_moments(tree):
    """Mixed-monoid mesh combine: psum the sums, pmin/pmax the extrema."""
    out = tree_psum({k: v for k, v in tree.items() if k not in ("mn", "mx")})
    out["mn"] = tree_pmin({"mn": tree["mn"]})["mn"]
    out["mx"] = tree_pmax({"mx": tree["mx"]})["mx"]
    return out


_jit_moments = jax.jit(_local_moments)


def _rank_1d(x):
    """Tie-averaged ranks of one vector (Spearman building block)."""
    s = jnp.sort(x)
    left = jnp.searchsorted(s, x, side="left")
    right = jnp.searchsorted(s, x, side="right")
    return 0.5 * (left + right + 1).astype(jnp.float32)


@jax.jit
def _ranks(X, m):
    """Tie-averaged ranks per column. Masked rows are pushed to +inf so
    every real row's rank is unaffected; their own ranks are weighted out
    downstream."""
    return jax.vmap(_rank_1d, in_axes=1, out_axes=1)(
        jnp.where(m[:, None] > 0, X, _BIG))


@jax.jit
def _rank_vec(y, m):
    return _rank_1d(jnp.where(m > 0, y, _BIG))


@jax.jit
def _contingency(X, y_onehot_masked):
    return X.T @ y_onehot_masked


@functools.partial(jax.jit, static_argnames=("in_sharding", "out_sharding"))
def _feature_corr_jit(Xr, m, in_sharding=None, out_sharding=None):
    mm = m[:, None]
    cnt = jnp.maximum(jnp.sum(m), 1.0)
    mean = jnp.sum(Xr * mm, axis=0) / cnt
    Xc = (Xr - mean) * mm
    sd = jnp.sqrt(jnp.maximum(jnp.sum(Xc * Xc, axis=0) / cnt, 1e-12))
    Z = Xc / sd
    if in_sharding is not None:
        Z = jax.lax.with_sharding_constraint(Z, in_sharding)
        C = (Z.T @ Z) / cnt
        return jax.lax.with_sharding_constraint(C, out_sharding)
    return (Z.T @ Z) / cnt


def _feature_corr(Xr, m, mesh_ctx):
    """Weighted correlation matrix of (rank-)columns as one MXU matmul.
    Under a mesh: rows contract over "data" (XLA inserts the psum) and the
    [d,d] output shards its leading axis over "model" — the feature-width
    (tensor-parallel-like) decomposition for O(d²) stats. Shardings ride as
    hashable static args so the compiled program caches per shape+mesh."""
    if mesh_ctx is None:
        return _feature_corr_jit(Xr, m)
    return _feature_corr_jit(
        Xr, m,
        in_sharding=NamedSharding(mesh_ctx.mesh, P(pmesh.DATA_AXIS, None)),
        out_sharding=NamedSharding(mesh_ctx.mesh, P(pmesh.MODEL_AXIS, None)))


class SanityChecker(Estimator):
    """(label, features) -> cleaned features."""

    in_types = (ft.RealNN, ft.OPVector)
    out_type = ft.OPVector

    def __init__(self,
                 max_correlation: float = 0.95,
                 min_correlation: float = 0.0,
                 min_variance: float = 1e-5,
                 max_feature_correlation: float = 0.99,
                 max_cramers_v: float = 0.95,
                 max_rule_confidence: float = 1.0,
                 min_required_rule_support: float = 0.001,
                 remove_feature_group: bool = True,
                 protect_text_shared_hash: bool = True,
                 correlation_type: str = "pearson",
                 correlation_exclusion: str = "none",
                 compute_feature_corr: bool = True,
                 max_feature_corr_width: int = 4096,
                 sample_upper_limit: int = 1_000_000,
                 sample_seed: int = 42,
                 categorical_label_max_classes: int = 100,
                 uid: Optional[str] = None):
        if correlation_type not in ("pearson", "spearman"):
            raise ValueError(
                f"correlation_type must be pearson|spearman, got "
                f"{correlation_type!r}")
        if correlation_exclusion not in ("none", "hashed_text"):
            raise ValueError(
                f"correlation_exclusion must be none|hashed_text, got "
                f"{correlation_exclusion!r}")
        self.max_correlation = max_correlation
        self.min_correlation = min_correlation
        self.min_variance = min_variance
        self.max_feature_correlation = max_feature_correlation
        self.max_cramers_v = max_cramers_v
        self.max_rule_confidence = max_rule_confidence
        self.min_required_rule_support = min_required_rule_support
        self.remove_feature_group = remove_feature_group
        self.protect_text_shared_hash = protect_text_shared_hash
        self.correlation_type = correlation_type
        self.correlation_exclusion = correlation_exclusion
        self.compute_feature_corr = compute_feature_corr
        self.max_feature_corr_width = max_feature_corr_width
        self.sample_upper_limit = sample_upper_limit
        self.sample_seed = sample_seed
        self.categorical_label_max_classes = categorical_label_max_classes
        super().__init__(uid=uid)

    def fit_model(self, data) -> "DropIndicesModel":
        label_name, feat_name = self.input_names
        col = data.device_col(feat_name)
        X = col.values
        meta: Optional[VectorMetadata] = col.metadata
        y = data.device_col(label_name).values
        n = data.n_rows  # logical rows (device arrays may be mesh-padded)
        d = int(X.shape[1])
        names = (meta.col_names() if meta is not None and meta.size == d
                 else [f"col_{j}" for j in range(d)])
        mask = data.row_mask()

        # ---- row-sampling cap (reference sampleUpperLimit, :60-92) ---------
        sample_fraction = 1.0
        if n > self.sample_upper_limit:
            rng = np.random.default_rng(self.sample_seed)
            idx = np.sort(rng.choice(n, size=self.sample_upper_limit,
                                     replace=False))
            jidx = jnp.asarray(idx)
            X, y = X[jidx], y[jidx]
            mask = jnp.ones(idx.size, jnp.float32)
            X = pmesh.pad_and_shard_rows(X)
            y = pmesh.pad_and_shard_rows(y)
            mask = pmesh.pad_and_shard_rows(mask)
            sample_fraction = self.sample_upper_limit / n
            n_used = self.sample_upper_limit
        else:
            n_used = n

        # ---- moment + correlation monoid pass ------------------------------
        if self.correlation_type == "spearman":
            Xr = _ranks(X, mask)
            yr = _rank_vec(y, mask)
        else:
            Xr, yr = X, y

        ctx = pmesh.current_mesh()
        rows = int(X.shape[0])
        use_mesh = ctx is not None and rows % ctx.n_data == 0
        if use_mesh:
            stats = mesh_reduce_stats(ctx, _local_moments, X, Xr, y, yr, mask,
                                      reduce=_combine_moments)
        else:
            stats = _jit_moments(X, Xr, y, yr, mask)
        stats = {k: np.asarray(v, np.float64) for k, v in stats.items()}
        cnt = max(stats["cnt"], 1.0)
        mean = stats["sx"] / cnt
        var = np.maximum(stats["sx2"] / cnt - mean ** 2, 0.0)
        xmin, xmax = stats["mn"], stats["mx"]
        mean_r = stats["sr"] / cnt
        var_r = np.maximum(stats["sr2"] / cnt - mean_r ** 2, 1e-12)
        ymean = stats["sy"] / cnt
        yvar = max(stats["sy2"] / cnt - ymean ** 2, 1e-12)
        cov = stats["sry"] / cnt - mean_r * ymean
        corr = cov / (np.sqrt(var_r) * np.sqrt(yvar))

        # columns excluded from every correlation rule (reference
        # CorrelationExclusion.HashedText)
        corr_excluded: set[int] = set()
        if self.correlation_exclusion == "hashed_text" and meta is not None \
                and meta.size == d:
            corr_excluded = {j for j, cm in enumerate(meta.columns)
                            if _is_text_shared_hash(cm)}

        # categorical groups from provenance metadata
        groups: dict[str, list[int]] = {}
        if meta is not None and meta.size == d:
            for j, cm in enumerate(meta.columns):
                g = cm.feature_group()
                if g is not None and cm.indicator_value is not None:
                    groups.setdefault(g, []).append(j)

        # contingency stats per group via one matmul for all columns
        cat_stats: dict[str, dict] = {}
        y_np = np.asarray(y)
        m_np = np.asarray(mask)
        classes = np.unique(y_np[m_np > 0])
        if groups and classes.size <= self.categorical_label_max_classes \
                and classes.size >= 2:
            y_onehot = (y_np[:, None] == classes[None, :]).astype(np.float32)
            y_onehot *= m_np[:, None]  # padded rows contribute nothing
            M = np.asarray(_contingency(X, jnp.asarray(y_onehot)), np.float64)
            for g, idxs in groups.items():
                cs = contingency_stats(M[idxs])
                cat_stats[g] = {
                    "cramersV": cs.cramers_v,
                    "mutualInfo": cs.mutual_info,
                    "maxRuleConfidences": cs.max_rule_confidences.tolist(),
                    "supports": cs.supports.tolist(),
                }

        # feature-feature correlation matrix (one MXU matmul)
        fcorr = None
        if self.compute_feature_corr and d <= self.max_feature_corr_width:
            fcorr = np.asarray(_feature_corr(Xr, mask, ctx if use_mesh
                                             else None), np.float64)

        # ---- drop decisions (reference DerivedFeatureFilterUtils.
        # reasonsToRemove ordering) ------------------------------------------
        col_stats = [ColumnStats(names[j], mean[j], var[j], xmin[j], xmax[j],
                                 float("nan") if j in corr_excluded
                                 else corr[j])
                     for j in range(d)]
        for j, c in enumerate(col_stats):
            if c.variance <= self.min_variance:
                c.reasons.append("variance too low")
            if j in corr_excluded:
                continue
            acorr = abs(c.corr_label)
            if np.isfinite(acorr):
                if acorr > self.max_correlation:
                    c.reasons.append("label correlation too high (leakage)")
                elif acorr < self.min_correlation:
                    c.reasons.append("label correlation too low")
        if fcorr is not None and self.max_feature_correlation < 1.0:
            # drop the LATER column of a too-correlated pair (reference:
            # featureCorrs.take(cl.index) — only earlier columns considered);
            # one vectorized pass over the strict lower triangle, Python only
            # touches actual hits
            lower = np.tril(fcorr, -1)
            A = np.where(np.isfinite(lower), np.abs(lower), 0.0)
            if corr_excluded:
                excl = np.zeros(d, bool)
                excl[list(corr_excluded)] = True
                A[excl, :] = 0.0
                A[:, excl] = 0.0
            over = A > self.max_feature_correlation
            first_i = np.argmax(over, axis=1)  # first too-correlated earlier col
            for j in np.nonzero(over.any(axis=1))[0]:
                i = int(first_i[j])
                col_stats[j].reasons.append(
                    f"feature correlation {fcorr[j, i]:.4f} with "
                    f"{names[i]} too high")
        group_dropped: set[str] = set()
        for g, idxs in groups.items():
            st = cat_stats.get(g)
            if st is None:
                continue
            if st["cramersV"] > self.max_cramers_v:
                group_dropped.add(g)
                for j in idxs:
                    col_stats[j].reasons.append("Cramér's V too high (leakage)")
            else:
                conf = np.asarray(st["maxRuleConfidences"])
                sup = np.asarray(st["supports"])
                if np.any((conf >= self.max_rule_confidence)
                          & (sup >= self.min_required_rule_support)):
                    group_dropped.add(g)
                    for j in idxs:
                        col_stats[j].reasons.append(
                            "association rule confidence too high")
        if self.remove_feature_group and meta is not None and meta.size == d:
            # a label-corr/Cramér's-V drop on any indicator removes its whole
            # group (reference parentCramersV/parentCorr), except protected
            # text shared-hash columns
            for g, idxs in groups.items():
                if g in group_dropped:
                    continue
                if any("leakage" in r for j in idxs
                       for r in col_stats[j].reasons):
                    for j in idxs:
                        if self.protect_text_shared_hash and \
                                _is_text_shared_hash(meta.columns[j]):
                            continue
                        if not col_stats[j].reasons:
                            col_stats[j].reasons.append(
                                "feature group removed (leaky sibling)")

        keep = [j for j, c in enumerate(col_stats) if not c.reasons]
        if not keep:
            # never drop everything: keep the highest-|corr| column
            with np.errstate(invalid="ignore"):
                acorr = np.abs(corr)
            acorr[~np.isfinite(acorr)] = -1.0
            j = int(np.argmax(acorr))
            col_stats[j].reasons.clear()
            keep = [j]
        for c in col_stats:
            c.dropped = bool(c.reasons)

        summary = SanityCheckerSummary(
            n_rows=n_used, names=names, column_stats=col_stats,
            categorical_stats=cat_stats,
            dropped=[c.name for c in col_stats if c.dropped],
            feature_corr=fcorr.tolist() if fcorr is not None else None,
            correlation_type=self.correlation_type,
            sample_fraction=sample_fraction)
        new_meta = meta.select(keep) if meta is not None and meta.size == d \
            else None
        return DropIndicesModel(keep_indices=keep, out_meta=new_meta,
                                summary=summary)


class DropIndicesModel(DeviceTransformer):
    """Gathers the kept columns; reindexed provenance metadata rides along."""

    in_types = (ft.RealNN, ft.OPVector)
    out_type = ft.OPVector

    def __init__(self, keep_indices=(), out_meta: Optional[VectorMetadata] = None,
                 summary: Optional[SanityCheckerSummary] = None,
                 uid: Optional[str] = None):
        self.keep_indices = [int(i) for i in keep_indices]
        self.out_meta = out_meta
        self.summary = summary
        super().__init__(uid=uid)

    def runtime_input_names(self):
        return (self.input_names[1],) if len(self.input_names) == 2 \
            else self.input_names

    def device_params(self):
        return jnp.asarray(self.keep_indices, jnp.int32)

    def device_apply(self, params, col: fr.VectorColumn) -> fr.VectorColumn:
        meta = self.out_meta
        if meta is None and col.metadata is not None \
                and col.metadata.size == int(col.values.shape[1]):
            meta = col.metadata.select(self.keep_indices)
        return fr.VectorColumn(jnp.take(col.values, params, axis=1), meta)

    def transform_row(self, *values):
        vec = np.asarray(values[-1], dtype=np.float32)
        return vec[np.asarray(self.keep_indices, dtype=np.int64)]

    def config(self):
        return {
            "keep_indices": self.keep_indices,
            "out_meta": self.out_meta.to_json() if self.out_meta else None,
            "summary": self.summary.to_json() if self.summary else None,
        }

    @classmethod
    def from_config(cls, config, uid=None):
        meta = (VectorMetadata.from_json(config["out_meta"])
                if config.get("out_meta") else None)
        return cls(keep_indices=config.get("keep_indices", ()),
                   out_meta=meta, uid=uid)
