from transmogrifai_tpu.preparators.sanity_checker import (
    DropIndicesModel, SanityChecker, SanityCheckerSummary,
)

__all__ = ["DropIndicesModel", "SanityChecker", "SanityCheckerSummary"]
