"""Streaming readers: micro-batch file streams for continuous scoring.

Parity: reference ``readers/StreamingReaders.scala`` / ``StreamingReader.
scala`` — avro file streams consumed by Spark DStreams for the runner's
``StreamingScore`` mode. The TPU-native design replaces DStreams with a
micro-batch pull loop: a ``StreamingReader`` yields batches of records; the
scoring side wraps each batch in the model's fitted DAG (compiled programs
are cached across batches, so steady-state batches replay jitted XLA with no
retrace as long as batch shape buckets repeat).
"""

from __future__ import annotations

import glob
import json
import os
import time
import warnings
from typing import Any, Callable, Iterable, Iterator, Optional, Sequence, Union

from transmogrifai_tpu.readers.base import CustomReader, DataReader

__all__ = ["StreamingReader", "FileStreamingReader", "StreamCheckpoint",
           "stream_score"]


class StreamCheckpoint:
    """Durable per-file progress for a file stream (the recovery analog of
    reference Spark DStream checkpointing, ``StreamingReaders.scala:40-67``:
    a restarted stream must neither re-score completed batches nor skip
    batches that were in flight when the process died).

    One JSON file records each fully-processed source file with a
    (mtime, size) fingerprint; writes are atomic (tmp + rename). A file is
    marked done only AFTER its batch has been consumed downstream, so a
    crash mid-batch replays that batch on restart (at-least-once, and
    exactly-once when the consumer's write is idempotent per batch)."""

    def __init__(self, path: str):
        self.path = path
        self._done: dict[str, dict] = {}
        #: file -> fingerprint-at-skip-time (None: the file was GONE when
        #: skipped). A skip only holds while the path's content matches —
        #: a file recreated at a skipped path is new data, not the skip
        self._skipped: dict[str, Optional[dict]] = {}
        if os.path.exists(path):
            try:
                with open(path) as fh:
                    state = json.load(fh)
                self._done = dict(state.get("done", {}))
                raw_skipped = state.get("skipped", [])
                # pre-fingerprint format stored a bare name list: load as
                # fingerprint-None (re-examined if the path has a file)
                self._skipped = dict(raw_skipped) \
                    if isinstance(raw_skipped, dict) \
                    else {f: None for f in raw_skipped}
            except (OSError, json.JSONDecodeError):
                warnings.warn(f"StreamCheckpoint: unreadable state at "
                              f"{path!r}; starting fresh", RuntimeWarning)

    @staticmethod
    def _fingerprint(f: str) -> Optional[dict]:
        """(mtime_ns, size) identity of one file. Nanosecond mtime, not
        the float ``st_mtime``: a file REWRITTEN in place within the
        float's granularity (same size, same truncated mtime — exactly
        what a fast producer's overwrite does) must not be treated as
        already processed. Falls back to the float where the platform
        lacks ``st_mtime_ns``. Entries recorded by the pre-``mtime_ns``
        format no longer match and replay once — at-least-once, the
        checkpoint's documented degradation."""
        try:
            st = os.stat(f)
            fp = {"mtime": st.st_mtime, "size": st.st_size}
            ns = getattr(st, "st_mtime_ns", None)
            if ns is not None:
                fp["mtime_ns"] = int(ns)
            return fp
        except OSError:
            return None

    def is_done(self, f: str) -> bool:
        fp = self._done.get(f)
        return fp is not None and fp == self._fingerprint(f)

    @property
    def skipped(self) -> list[str]:
        return list(self._skipped)

    def is_skipped(self, f: str) -> bool:
        """True while the durable skip still applies: the path has no
        file (a disappeared/rotated source stays skipped) or the file is
        byte-identical to when it was abandoned. A file RECREATED at a
        skipped path (the rotation pattern: rename away, write fresh) no
        longer matches and is read as new data."""
        if f not in self._skipped:
            return False
        cur = self._fingerprint(f)
        if cur is None:
            return True
        return self._skipped[f] == cur

    def mark_done(self, f: str, fingerprint: Optional[dict] = None) -> None:
        """Record ``f`` as fully processed. Pass the fingerprint captured
        BEFORE the file was read: if a producer appended rows between read
        and commit, the stored (pre-append) fingerprint no longer matches
        and the file is re-processed on restart instead of silently
        losing the appended rows."""
        fp = fingerprint if fingerprint is not None else self._fingerprint(f)
        if fp is not None:
            self._done[f] = fp
            self._skipped.pop(f, None)
            self._save()

    def mark_skipped(self, f: str) -> None:
        fp = self._fingerprint(f)
        if f not in self._skipped or self._skipped[f] != fp:
            self._skipped[f] = fp
            self._save()

    def _save(self) -> None:
        """Atomic, best-effort (``utils.durable``): a checkpoint-write
        failure must degrade to at-least-once replay on restart (batch
        re-scored), never kill a stream whose scoring is healthy."""
        from transmogrifai_tpu.utils.durable import (
            atomic_json_dump, best_effort_checkpoint_write,
        )

        def write() -> None:
            d = os.path.dirname(self.path)
            if d:
                os.makedirs(d, exist_ok=True)
            atomic_json_dump({"done": self._done, "skipped": self._skipped},
                             self.path)

        best_effort_checkpoint_write(
            write,
            f"StreamCheckpoint: write to {self.path!r} failed; progress "
            "not persisted — a restart may replay recent batches")


class StreamingReader:
    """Abstract micro-batch source: iterate lists of records."""

    def stream(self) -> Iterator[list[Any]]:
        raise NotImplementedError


class FileStreamingReader(StreamingReader):
    """Watches a directory; every new file becomes one micro-batch.

    ``make_reader`` maps a file path to a batch ``DataReader`` (csv/avro/
    parquet); defaults by extension. Files present before the first poll are
    processed unless ``new_files_only``. The loop stops after ``max_batches``
    batches or ``timeout_s`` without new files (both optional — leave unset
    for a long-running scorer).
    """

    def __init__(self, path: str,
                 pattern: str = "*",
                 make_reader: Optional[Callable[[str], DataReader]] = None,
                 schema: Optional[dict] = None,
                 poll_interval_s: float = 1.0,
                 new_files_only: bool = False,
                 max_batches: Optional[int] = None,
                 timeout_s: Optional[float] = None,
                 checkpoint: Optional[Union[str, StreamCheckpoint]] = None):
        self.path = path
        self.pattern = pattern
        #: optional durable progress: a restarted reader resumes after the
        #: last file whose batch was fully consumed (see StreamCheckpoint)
        self.checkpoint = (StreamCheckpoint(checkpoint)
                           if isinstance(checkpoint, str) else checkpoint)
        #: {column: FeatureType} forced onto each batch file; without it the
        #: per-file readers infer their own (which can disagree with the
        #: model's raw feature types — stream_score fills it from the model)
        self.schema = schema
        self.make_reader = make_reader or (
            lambda p: reader_for_file(p, self.schema))
        self.poll_interval_s = poll_interval_s
        self.new_files_only = new_files_only
        self.max_batches = max_batches
        self.timeout_s = timeout_s
        #: files abandoned after ``max_retries_per_file`` failed reads —
        #: operators should monitor this for silent data loss
        self.skipped_files: list[str] = []
        #: source file of the most recently yielded batch
        self.current_file: Optional[str] = None

    def _list_files(self) -> list[str]:
        return sorted(glob.glob(os.path.join(self.path, self.pattern)))

    #: reads of one file are retried this many polls before it is skipped
    #: (covers producers that write in place; atomic rename-into-place is
    #: still the recommended convention, as with Spark file streams)
    max_retries_per_file = 3

    def stream(self) -> Iterator[list[Any]]:
        seen: set[str] = set(self._list_files()) if self.new_files_only \
            else set()
        if self.checkpoint is not None:
            # resume: completed files (fingerprint still matching) and
            # previously-abandoned files (skip fingerprint still matching —
            # a file RECREATED at a skipped path is new data) not replayed
            seen.update(f for f in self._list_files()
                        if self.checkpoint.is_done(f)
                        or self.checkpoint.is_skipped(f))
        failures: dict[str, int] = {}
        next_retry: dict[str, float] = {}
        n_batches = 0
        last_new = time.monotonic()
        while True:
            now = time.monotonic()
            new_files = [f for f in self._list_files()
                         if f not in seen and next_retry.get(f, 0.0) <= now]
            for f in new_files:
                last_new = time.monotonic()
                try:
                    reader = self.make_reader(f)
                except ValueError:
                    # no reader for this extension (e.g. a sidecar .avsc
                    # schema file): skip it permanently, never retry
                    seen.add(f)
                    continue
                read_fp = (StreamCheckpoint._fingerprint(f)
                           if self.checkpoint is not None else None)
                try:
                    # chaos seam: an injected host-IO fault here follows
                    # the exact partially-written-file path below (retry
                    # next poll, abandon after max_retries_per_file)
                    from transmogrifai_tpu.utils.faults import fault_point
                    fault_point("ingest.read")
                    records = list(reader.read())
                except Exception as read_err:
                    from transmogrifai_tpu.utils.faults import (
                        FaultHarnessError,
                    )
                    if isinstance(read_err, FaultHarnessError):
                        raise  # injected crash / misconfigured plan: die
                    if not os.path.exists(f):
                        # deleted/rotated between _list_files and the
                        # read: the rows are GONE — retrying would only
                        # delay the stream. Warn-and-skip (durably, so a
                        # restart doesn't wait on it either); operators
                        # monitor skipped_files for rotation-induced loss
                        seen.add(f)
                        self.skipped_files.append(f)
                        if self.checkpoint is not None:
                            self.checkpoint.mark_skipped(f)
                        warnings.warn(
                            f"FileStreamingReader: {f!r} disappeared "
                            "mid-stream (deleted/rotated between listing "
                            "and read); skipping it", RuntimeWarning)
                        continue
                    # likely a partially-written file: retry on a later
                    # poll (one attempt per poll interval, so a slow
                    # producer gets real wall-clock time to finish), give
                    # up after max_retries_per_file attempts
                    failures[f] = failures.get(f, 0) + 1
                    if failures[f] >= self.max_retries_per_file:
                        seen.add(f)
                        self.skipped_files.append(f)
                        if self.checkpoint is not None:
                            self.checkpoint.mark_skipped(f)
                        warnings.warn(
                            f"FileStreamingReader: abandoning {f!r} after "
                            f"{failures[f]} failed reads — batch dropped "
                            "from the score stream", RuntimeWarning)
                    else:
                        next_retry[f] = time.monotonic() + \
                            self.poll_interval_s
                    continue
                seen.add(f)
                #: source of the batch currently in flight — consumers that
                #: need idempotent per-batch outputs key off this
                self.current_file = f
                if records:
                    n_batches += 1
                    yield records
                    # the consumer has finished this batch iff it asked for
                    # the next one — commit AFTER resume (with the
                    # fingerprint captured at READ time), so a crash
                    # mid-batch replays the file on restart
                    if self.checkpoint is not None:
                        self.checkpoint.mark_done(f, read_fp)
                elif self.checkpoint is not None:
                    self.checkpoint.mark_done(f, read_fp)  # empty file
                if self.max_batches and n_batches >= self.max_batches:
                    return
            if not new_files:
                if self.timeout_s is not None and \
                        time.monotonic() - last_new > self.timeout_s:
                    return
                time.sleep(self.poll_interval_s)


def reader_for_file(path: str, schema: Optional[dict] = None) -> DataReader:
    """Default path -> batch reader dispatch by extension."""
    ext = os.path.splitext(path)[1].lower()
    if ext == ".csv":
        from transmogrifai_tpu.readers.csv import CSVReader
        return CSVReader(path, schema=schema)
    if ext == ".avro":
        from transmogrifai_tpu.readers.avro import AvroReader
        return AvroReader(path, schema=schema)
    if ext in (".parquet", ".pq"):
        from transmogrifai_tpu.readers.parquet import ParquetReader
        return ParquetReader(path, schema=schema)
    raise ValueError(f"No streaming reader for extension {ext!r} ({path})")


def stream_score(model, reader: StreamingReader,
                 write_batch: Optional[Callable[[Any, int], None]] = None,
                 prefetch: Optional[int] = None) -> Iterator[Any]:
    """Continuous scoring loop (reference OpWorkflowRunner StreamingScore):
    for each micro-batch, run the fitted DAG and yield the scored frame
    (and/or hand it to ``write_batch(frame, batch_index)``).

    Round 14 double buffer: the HOST half of ingest (record decode ->
    typed raw columns, ``WorkflowModel._ingest_frame``) for batch N+1 runs
    on a background prefetch thread while batch N's fused FE program
    executes on device, so host IO overlaps device compute instead of
    serializing with it. ``prefetch`` overrides
    ``TRANSMOGRIFAI_PREFETCH_DEPTH`` (0 = the serial pre-round-14 loop,
    byte-for-byte). Device dispatch stays on the consumer thread; waits
    are dispatch-watchdog-armed (site ``ingest.prefetch``)."""
    from transmogrifai_tpu.ingest_fusion import ChunkPrefetcher
    pinned = getattr(reader, "schema", ...) is None
    if pinned:
        # pin batch-file parsing to the model's raw predictor types so
        # per-file inference cannot disagree with the fitted pipeline
        # (responses stay inferred: score streams usually lack them)
        reader.schema = {f.name: f.ftype for f in model.raw_features
                         if not f.is_response}
    if getattr(reader, "checkpoint", None) is not None:
        # a durable stream commits a file as done when the NEXT batch is
        # pulled — prefetching would advance the source generator (and the
        # commit) ahead of actual consumption, breaking the at-least-once
        # crash-replay contract. Durability outranks overlap: run serial.
        prefetch = 0
    prefetcher = ChunkPrefetcher(
        reader.stream(),
        lambda records: model._ingest_frame(CustomReader(records=records)),
        depth=prefetch)
    try:
        for i, frame in enumerate(prefetcher):
            scored = model.score(frame)
            if write_batch is not None:
                write_batch(scored, i)
            yield scored
    finally:
        prefetcher.close()
        if pinned:
            reader.schema = None  # don't leak this model's types
