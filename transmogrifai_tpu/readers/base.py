"""Data readers: records -> raw-feature HostFrame.

Parity: reference ``readers/src/main/scala/com/salesforce/op/readers/
DataReader.scala:58-208`` — ``generateDataFrame(rawFeatures)`` runs every
``FeatureGeneratorStage.extract_fn`` per record and builds the raw frame with
an optional entity-key column. Here the result is a columnar ``HostFrame``
(device residency happens lazily downstream), so the per-record loop is the
ingest boundary, not the compute hot loop.

Scale design: ingest is CHUNKED — records stream through a bounded buffer
and each chunk converts straight to typed numpy columns, so the python-dict
representation of the dataset never fully materializes (the Spark
partition-at-a-time analog). ``summarize`` computes per-column streaming
statistics (fill counts, extrema, a C++ StreamingHistogram quantile sketch)
in one pass with NO frame at all — the on-ramp for fits at row counts that
don't fit host memory as python objects.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Iterable, Iterator, Optional, Sequence

import numpy as np

from transmogrifai_tpu.features.feature import FeatureLike
from transmogrifai_tpu.frame import HostColumn, HostFrame, NUMERIC_KINDS
from transmogrifai_tpu.stages.base import FeatureGeneratorStage

__all__ = ["DataReader", "CustomReader", "ColumnSummary"]


@dataclass
class ColumnSummary:
    """Streaming per-column ingest statistics (reference Summary.scala +
    FeatureDistribution's first map-reduce pass)."""

    name: str
    ftype_name: str
    count: int = 0
    nulls: int = 0
    min: float = float("inf")
    max: float = float("-inf")
    histogram: Optional[Any] = None   # StreamingHistogram for numeric kinds

    @property
    def fill_rate(self) -> float:
        return 1.0 - self.nulls / max(self.count, 1)

    def quantiles(self, qs) -> np.ndarray:
        if self.histogram is None:
            raise ValueError(f"{self.name}: no histogram (non-numeric)")
        return self.histogram.quantiles(qs)


class DataReader:
    """Abstract reader of records (python dicts or objects)."""

    #: rows per ingest chunk: bounds the transient python-object footprint
    chunk_rows: int = 65536

    #: matches FeatureBuilder .source(tag) bindings in joined readers
    source_tag: Optional[str] = None

    def __init__(self, key_fn: Optional[Callable[[Any], str]] = None):
        self.key_fn = key_fn

    def with_source_tag(self, tag: str) -> "DataReader":
        """Tag this reader so joined readers can route explicitly-bound
        (extracted, non-column) features to it."""
        self.source_tag = tag
        return self

    def read(self) -> Iterable[Any]:
        raise NotImplementedError

    def available_columns(self) -> Optional[set]:
        """Column names this reader can produce, or None when unknown.
        Lets scoring drop absent response features instead of failing."""
        return None

    # -- joins (reference Reader.leftOuterJoin/innerJoin) --------------------
    def left_outer_join(self, other: "DataReader", join_keys=None):
        from transmogrifai_tpu.readers.joined import JoinedDataReader, JoinKeys
        return JoinedDataReader(self, other, join_keys or JoinKeys(),
                                "left-outer")

    def inner_join(self, other: "DataReader", join_keys=None):
        from transmogrifai_tpu.readers.joined import JoinedDataReader, JoinKeys
        return JoinedDataReader(self, other, join_keys or JoinKeys(), "inner")

    def _iter_chunks(self) -> Iterator[list]:
        """Bounded-buffer record chunks; at least one (possibly empty)."""
        buf: list = []
        any_yielded = False
        for r in self.read():
            buf.append(r)
            if len(buf) >= self.chunk_rows:
                yield buf
                any_yielded = True
                buf = []
        if buf or not any_yielded:
            yield buf

    # -- raw data generation -------------------------------------------------
    def generate_frame(self, raw_features: Sequence[FeatureLike]) -> HostFrame:
        from transmogrifai_tpu.utils.tracing import span
        stages = [_origin(f) for f in raw_features]
        # schema resolution hoisted ONCE per reader (HostColumn.builder):
        # the per-column kind dispatch used to re-run on every chunk, which
        # streaming micro-batch ingest paid per batch
        builders = [HostColumn.builder(f.ftype) for f in raw_features]
        chunk_cols: dict[str, list[HostColumn]] = {f.name: []
                                                   for f in raw_features}
        key_chunks: Optional[list] = [] if self.key_fn is not None else None
        with span("reader.generate_frame", reader=type(self).__name__,
                  n_features=len(raw_features)):
            for chunk in self._iter_chunks():
                for f, stage, build in zip(raw_features, stages, builders):
                    vals = [stage.extract(r) for r in chunk]
                    chunk_cols[f.name].append(build(vals))
                if key_chunks is not None:
                    key_chunks.append(np.asarray(
                        [str(self.key_fn(r)) for r in chunk], dtype=object))
            cols = {name: HostColumn.concat(chunks)
                    for name, chunks in chunk_cols.items()}
            key = np.concatenate(key_chunks) if key_chunks else None
            return HostFrame(cols, key)

    # -- streaming statistics (no frame materialization) ---------------------
    def summarize(self, raw_features: Sequence[FeatureLike],
                  max_bins: int = 100) -> dict[str, ColumnSummary]:
        """One streaming pass over the records: per-column fill counts,
        extrema, and (numerics) a mergeable quantile sketch. Host memory is
        O(chunk_rows + max_bins per column) regardless of row count."""
        from transmogrifai_tpu.utils.streaming_histogram import (
            StreamingHistogram,
        )
        stages = [_origin(f) for f in raw_features]
        out = {f.name: ColumnSummary(
            name=f.name, ftype_name=f.ftype.__name__,
            histogram=(StreamingHistogram(max_bins=max_bins)
                       if f.ftype.device_kind in NUMERIC_KINDS else None))
            for f in raw_features}
        for chunk in self._iter_chunks():
            if not chunk:
                continue
            for f, stage in zip(raw_features, stages):
                s = out[f.name]
                s.count += len(chunk)
                if s.histogram is not None:
                    # values go through the SAME type validation ingest
                    # applies — summary statistics must describe exactly
                    # the data generate_frame would accept
                    validated = [f.ftype._validate(stage.extract(r))
                                 for r in chunk]
                    present = np.asarray(
                        [v for v in validated if v is not None], np.float64)
                    s.nulls += len(chunk) - present.size
                    if present.size:
                        s.min = min(s.min, float(present.min()))
                        s.max = max(s.max, float(present.max()))
                        s.histogram.update_all(present)
                else:
                    for r in chunk:
                        v = f.ftype._validate(stage.extract(r))
                        if v is None or (hasattr(v, "__len__")
                                         and len(v) == 0):
                            s.nulls += 1
        return out


def _origin(f: FeatureLike) -> FeatureGeneratorStage:
    stage = f.origin_stage
    if not isinstance(stage, FeatureGeneratorStage):
        raise ValueError(
            f"Feature {f.name!r} is not raw (origin {type(stage).__name__}); "
            "readers generate raw features only")
    return stage


class CustomReader(DataReader):
    """Wraps an in-memory record collection or a HostFrame (the analog of
    ``setInputDataset``/``setInputRDD`` wrapping data in a CustomReader)."""

    def __init__(self, records: Optional[Iterable[Any]] = None,
                 frame: Optional[HostFrame] = None,
                 key_fn: Optional[Callable[[Any], str]] = None):
        super().__init__(key_fn)
        if (records is None) == (frame is None):
            raise ValueError("CustomReader: provide exactly one of records/frame")
        self.records = None if records is None else list(records)
        self.frame = frame

    def read(self) -> Iterable[Any]:
        if self.records is not None:
            return self.records
        return list(self.frame.iter_rows())

    def available_columns(self) -> Optional[set]:
        if self.frame is not None:
            return set(self.frame.names())
        if self.records and isinstance(self.records[0], dict):
            return set(self.records[0].keys())
        return None

    def generate_frame(self, raw_features: Sequence[FeatureLike]) -> HostFrame:
        if self.frame is not None:
            from transmogrifai_tpu.utils.tracing import span
            # fast path: columns already columnar; select + validate types
            missing = [f.name for f in raw_features if f.name not in self.frame]
            if missing:
                raise KeyError(f"Frame lacks raw feature columns {missing}")
            with span("reader.generate_frame", reader=type(self).__name__,
                      n_features=len(raw_features)):
                return self.frame.select([f.name for f in raw_features])
        return super().generate_frame(raw_features)
