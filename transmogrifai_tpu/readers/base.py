"""Data readers: records -> raw-feature HostFrame.

Parity: reference ``readers/src/main/scala/com/salesforce/op/readers/
DataReader.scala:58-208`` — ``generateDataFrame(rawFeatures)`` runs every
``FeatureGeneratorStage.extract_fn`` per record and builds the raw frame with
an optional entity-key column. Here the result is a columnar ``HostFrame``
(device residency happens lazily downstream), so the per-record loop is the
ingest boundary, not the compute hot loop.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Optional, Sequence

import numpy as np

from transmogrifai_tpu.features.feature import FeatureLike
from transmogrifai_tpu.frame import HostColumn, HostFrame
from transmogrifai_tpu.stages.base import FeatureGeneratorStage

__all__ = ["DataReader", "CustomReader"]


class DataReader:
    """Abstract reader of records (python dicts or objects)."""

    def __init__(self, key_fn: Optional[Callable[[Any], str]] = None):
        self.key_fn = key_fn

    def read(self) -> Iterable[Any]:
        raise NotImplementedError

    def available_columns(self) -> Optional[set]:
        """Column names this reader can produce, or None when unknown.
        Lets scoring drop absent response features instead of failing."""
        return None

    # -- joins (reference Reader.leftOuterJoin/innerJoin) --------------------
    def left_outer_join(self, other: "DataReader", join_keys=None):
        from transmogrifai_tpu.readers.joined import JoinedDataReader, JoinKeys
        return JoinedDataReader(self, other, join_keys or JoinKeys(),
                                "left-outer")

    def inner_join(self, other: "DataReader", join_keys=None):
        from transmogrifai_tpu.readers.joined import JoinedDataReader, JoinKeys
        return JoinedDataReader(self, other, join_keys or JoinKeys(), "inner")

    # -- raw data generation -------------------------------------------------
    def generate_frame(self, raw_features: Sequence[FeatureLike]) -> HostFrame:
        records = self.read()
        if not isinstance(records, (list, tuple)):
            records = list(records)
        stages = [_origin(f) for f in raw_features]
        cols = {}
        for f, stage in zip(raw_features, stages):
            vals = [stage.extract(r) for r in records]
            cols[f.name] = HostColumn.from_values(f.ftype, vals)
        key = None
        if self.key_fn is not None:
            key = np.asarray([str(self.key_fn(r)) for r in records], dtype=object)
        return HostFrame(cols, key)


def _origin(f: FeatureLike) -> FeatureGeneratorStage:
    stage = f.origin_stage
    if not isinstance(stage, FeatureGeneratorStage):
        raise ValueError(
            f"Feature {f.name!r} is not raw (origin {type(stage).__name__}); "
            "readers generate raw features only")
    return stage


class CustomReader(DataReader):
    """Wraps an in-memory record collection or a HostFrame (the analog of
    ``setInputDataset``/``setInputRDD`` wrapping data in a CustomReader)."""

    def __init__(self, records: Optional[Iterable[Any]] = None,
                 frame: Optional[HostFrame] = None,
                 key_fn: Optional[Callable[[Any], str]] = None):
        super().__init__(key_fn)
        if (records is None) == (frame is None):
            raise ValueError("CustomReader: provide exactly one of records/frame")
        self.records = None if records is None else list(records)
        self.frame = frame

    def read(self) -> Iterable[Any]:
        if self.records is not None:
            return self.records
        return list(self.frame.iter_rows())

    def available_columns(self) -> Optional[set]:
        if self.frame is not None:
            return set(self.frame.names())
        if self.records and isinstance(self.records[0], dict):
            return set(self.records[0].keys())
        return None

    def generate_frame(self, raw_features: Sequence[FeatureLike]) -> HostFrame:
        if self.frame is not None:
            # fast path: columns already columnar; select + validate types
            missing = [f.name for f in raw_features if f.name not in self.frame]
            if missing:
                raise KeyError(f"Frame lacks raw feature columns {missing}")
            return self.frame.select([f.name for f in raw_features])
        return super().generate_frame(raw_features)
