"""CSV ingestion with schema inference.

Parity: reference ``readers/CSVReaders.scala`` + ``CSVAutoReaders.scala`` —
CSV records with an explicit schema, or automatic schema inference over a
sample (the Spark-CSV inference analog): Integral, Real, Binary (true/false),
else Text. Empty cells are missing.
"""

from __future__ import annotations

import csv as _csv
from typing import Any, Iterable, Optional, Sequence

from transmogrifai_tpu.readers.base import DataReader
from transmogrifai_tpu.types import feature_types as ft

__all__ = ["CSVReader", "infer_csv_schema", "parse_cell"]

_TRUE = {"true", "t", "yes"}
_FALSE = {"false", "f", "no"}


def _is_int(s: str) -> bool:
    try:
        int(s)
        return True
    except ValueError:
        return False


def _is_float(s: str) -> bool:
    try:
        float(s)
        return True
    except ValueError:
        return False


def infer_csv_schema(rows: Sequence[dict[str, str]],
                     sample: int = 1000) -> dict[str, type[ft.FeatureType]]:
    """Infer a feature type per column from string cells: Binary (true/false
    literals) < Integral < Real < Text; all-empty columns default to Text."""
    if not rows:
        return {}
    names = list(rows[0].keys())
    schema: dict[str, type[ft.FeatureType]] = {}
    for name in names:
        seen = False
        could_bool = could_int = could_float = True
        for row in rows[:sample]:
            s = (row.get(name) or "").strip()
            if s == "":
                continue
            seen = True
            low = s.lower()
            if low not in _TRUE and low not in _FALSE:
                could_bool = False
            if not _is_int(s):
                could_int = False
            if not _is_float(s):
                could_float = False
            if not (could_bool or could_int or could_float):
                break
        if not seen:
            schema[name] = ft.Text
        elif could_bool:
            schema[name] = ft.Binary
        elif could_int:
            schema[name] = ft.Integral
        elif could_float:
            schema[name] = ft.Real
        else:
            schema[name] = ft.Text
    return schema


def parse_cell(s: Optional[str], ftype: type[ft.FeatureType]) -> Any:
    if s is None:
        return None
    s = s.strip()
    if s == "":
        return None
    kind = ftype.device_kind
    if kind == "binary":
        low = s.lower()
        if low in _TRUE:
            return True
        if low in _FALSE:
            return False
        return bool(int(s))
    if kind in ("integral", "date", "datetime"):
        return int(float(s))
    if kind == "real":
        return float(s)
    return s


class CSVReader(DataReader):
    """Reads a CSV into records of parsed python values.

    ``schema=None`` triggers inference over the first ``sample`` rows
    (csvAuto). ``header=False`` requires an explicit ``columns`` name list.
    """

    def __init__(self, path: str,
                 schema: Optional[dict[str, type[ft.FeatureType]]] = None,
                 header: bool = True,
                 columns: Optional[Sequence[str]] = None,
                 key_col: Optional[str] = None,
                 sample: int = 1000):
        super().__init__(key_fn=(lambda r: r[key_col]) if key_col else None)
        self.path = path
        self.header = header
        self.columns = list(columns) if columns else None
        self._schema = schema
        self.sample = sample

    def _raw_rows(self) -> list[dict[str, str]]:
        with open(self.path, newline="") as fh:
            if self.header:
                return list(_csv.DictReader(fh))
            if not self.columns:
                raise ValueError("header=False requires explicit columns")
            # skip blank lines (DictReader does this implicitly in header
            # mode; a trailing newline must not become an all-None row)
            return [dict(zip(self.columns, row))
                    for row in _csv.reader(fh) if row]

    @property
    def schema(self) -> dict[str, type[ft.FeatureType]]:
        if self._schema is None:
            self._schema = infer_csv_schema(self._raw_rows(), self.sample)
        return self._schema

    def available_columns(self):
        return set(self.schema)

    def read(self) -> Iterable[dict[str, Any]]:
        schema = self.schema
        out = []
        for row in self._raw_rows():
            out.append({name: parse_cell(row.get(name), t)
                        for name, t in schema.items()})
        return out
