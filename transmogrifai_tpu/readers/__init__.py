from transmogrifai_tpu.readers.base import CustomReader, DataReader
from transmogrifai_tpu.readers.csv import CSVReader, infer_csv_schema
from transmogrifai_tpu.readers.aggregates import (
    AggregateDataReader, ConditionalDataReader,
)
from transmogrifai_tpu.readers.factory import DataReaders

__all__ = [
    "CustomReader", "DataReader", "CSVReader", "infer_csv_schema",
    "AggregateDataReader", "ConditionalDataReader", "DataReaders",
]
