from transmogrifai_tpu.readers.base import CustomReader, DataReader
from transmogrifai_tpu.readers.csv import CSVReader, infer_csv_schema
from transmogrifai_tpu.readers.aggregates import (
    AggregateDataReader, ConditionalDataReader,
)
from transmogrifai_tpu.readers.avro import (
    AvroReader, feature_schema_of_avro, save_avro,
)
from transmogrifai_tpu.readers.factory import DataReaders
from transmogrifai_tpu.readers.joined import (
    JoinKeys, JoinedAggregateDataReader, JoinedDataReader, TimeBasedFilter,
)
from transmogrifai_tpu.readers.parquet import ParquetReader
from transmogrifai_tpu.readers.streaming import (
    FileStreamingReader, StreamingReader, stream_score,
)

__all__ = [
    "CustomReader", "DataReader", "CSVReader", "infer_csv_schema",
    "AggregateDataReader", "ConditionalDataReader", "DataReaders",
    "JoinKeys", "JoinedDataReader", "JoinedAggregateDataReader",
    "TimeBasedFilter", "AvroReader", "feature_schema_of_avro", "save_avro",
    "ParquetReader", "FileStreamingReader", "StreamingReader", "stream_score",
]
