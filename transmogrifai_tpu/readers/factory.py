"""Reader factory namespace.

Parity: reference ``readers/DataReaders.scala:44-270`` —
``DataReaders.Simple/Aggregate/Conditional x {csv, csvAuto, custom}``.
(Avro/Parquet variants land with the IO layer; the factory shape is stable.)
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Optional

from transmogrifai_tpu.readers.aggregates import (
    AggregateDataReader, ConditionalDataReader,
)
from transmogrifai_tpu.readers.avro import AvroReader
from transmogrifai_tpu.readers.base import CustomReader, DataReader
from transmogrifai_tpu.readers.csv import CSVReader

__all__ = ["DataReaders"]


class DataReaders:
    class Simple:
        @staticmethod
        def csv(path: str, schema=None, key_col: Optional[str] = None,
                **kw) -> CSVReader:
            return CSVReader(path, schema=schema, key_col=key_col, **kw)

        @staticmethod
        def csv_auto(path: str, key_col: Optional[str] = None, **kw) -> CSVReader:
            return CSVReader(path, schema=None, key_col=key_col, **kw)

        @staticmethod
        def avro(path: str, schema=None, key_col: Optional[str] = None
                 ) -> AvroReader:
            return AvroReader(path, schema=schema, key_col=key_col)

        @staticmethod
        def parquet(path: str, schema=None, key_col: Optional[str] = None,
                    **kw):
            from transmogrifai_tpu.readers.parquet import ParquetReader
            return ParquetReader(path, schema=schema, key_col=key_col, **kw)

        @staticmethod
        def custom(records: Iterable[Any],
                   key_fn: Optional[Callable[[Any], str]] = None) -> CustomReader:
            return CustomReader(records=records, key_fn=key_fn)

    class Streaming:
        """Micro-batch file streams (reference StreamingReaders.avro)."""

        @staticmethod
        def files(path: str, pattern: str = "*", **kw):
            from transmogrifai_tpu.readers.streaming import FileStreamingReader
            return FileStreamingReader(path, pattern=pattern, **kw)

        @staticmethod
        def avro(path: str, **kw):
            from transmogrifai_tpu.readers.streaming import FileStreamingReader
            return FileStreamingReader(path, pattern="*.avro", **kw)

    class Aggregate:
        @staticmethod
        def csv(path: str, key_fn, time_fn, cutoff_ms=None, schema=None,
                **kw) -> AggregateDataReader:
            return AggregateDataReader(
                CSVReader(path, schema=schema, **kw), key_fn, time_fn, cutoff_ms)

        @staticmethod
        def avro(path: str, key_fn, time_fn, cutoff_ms=None, schema=None
                 ) -> AggregateDataReader:
            return AggregateDataReader(
                AvroReader(path, schema=schema), key_fn, time_fn, cutoff_ms)

        @staticmethod
        def custom(records: Iterable[Any], key_fn, time_fn,
                   cutoff_ms=None) -> AggregateDataReader:
            return AggregateDataReader(
                CustomReader(records=records), key_fn, time_fn, cutoff_ms)

    class Conditional:
        @staticmethod
        def csv(path: str, key_fn, time_fn, condition_fn, schema=None,
                **kw) -> ConditionalDataReader:
            return ConditionalDataReader(
                CSVReader(path, schema=schema, **kw), key_fn, time_fn, condition_fn)

        @staticmethod
        def avro(path: str, key_fn, time_fn, condition_fn, schema=None
                 ) -> ConditionalDataReader:
            return ConditionalDataReader(
                AvroReader(path, schema=schema), key_fn, time_fn, condition_fn)

        @staticmethod
        def custom(records: Iterable[Any], key_fn, time_fn,
                   condition_fn) -> ConditionalDataReader:
            return ConditionalDataReader(
                CustomReader(records=records), key_fn, time_fn, condition_fn)
