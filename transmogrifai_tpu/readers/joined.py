"""Feature-aware joins of two readers.

Parity: reference ``readers/JoinedDataReader.scala:40-442`` — left-outer and
inner joins over two readers' generated frames with ``JoinKeys`` (left/right
key columns, result key), Spark-join row-duplication semantics (one output
row per matching left x right pair; unmatched left rows null-filled on a
left-outer join), time-based filtering (``TimeBasedFilter``) and post-join
re-aggregation of the right side (``aggregateRightData``).

TPU note: the reference joins Spark DataFrames (shuffle). Here both sides are
columnar ``HostFrame``s, so the join is a host-side hash join producing index
vectors and the column composition is ``HostColumn.take``-style gathers —
no row objects are materialized. Device residency stays lazy downstream.
"""

from __future__ import annotations

from collections import defaultdict
from dataclasses import dataclass
from typing import Any, Iterable, Optional, Sequence

import numpy as np

from transmogrifai_tpu.aggregators.monoid import (
    Event, FeatureAggregator, aggregator_of,
)
from transmogrifai_tpu.features.feature import FeatureLike
from transmogrifai_tpu.frame import HostColumn, HostFrame
from transmogrifai_tpu.readers.base import DataReader
from transmogrifai_tpu.types import feature_types as ft

__all__ = ["JoinKeys", "TimeBasedFilter", "JoinedDataReader",
           "JoinedAggregateDataReader"]

#: sentinel column name meaning "the frame's entity-key array"
KEY = "key"


@dataclass(frozen=True)
class JoinKeys:
    """Which columns to join on. ``"key"`` refers to the frame's entity key
    (reference ``JoinKeys`` with ``resultKey`` naming the joined key)."""
    left_key: str = KEY
    right_key: str = KEY
    result_key: str = KEY


@dataclass(frozen=True)
class TimeBasedFilter:
    """Keep right-side rows whose ``primary`` timestamp falls in
    ``[cutoff - window_ms, cutoff)`` where cutoff is the left row's
    ``condition`` timestamp (reference ``TimeBasedFilter`` — predictor
    boundaries follow ``FeatureAggregator.scala:108-125``: strictly before
    the cutoff, window-start inclusive; responses are ``>= cutoff``)."""
    condition: str   # left-side Date/DateTime feature name -> per-key cutoff
    primary: str     # right-side Date/DateTime feature name -> event time
    window_ms: int = 2**62


def _key_strings(frame: HostFrame, key_col: str) -> np.ndarray:
    if key_col == KEY:
        if frame.key is None:
            raise ValueError("join on entity key but reader produced no key "
                             "(set key_col/key_fn on the reader)")
        return np.asarray([str(k) for k in frame.key], dtype=object)
    col = frame[key_col]
    return np.asarray(
        [None if (v := col.python_value(i)) is None else str(v)
         for i in range(len(col))], dtype=object)


def _take_with_null(col: HostColumn, idx: np.ndarray) -> HostColumn:
    """Gather rows by index; ``idx < 0`` yields the type's empty value."""
    miss = idx < 0
    safe = np.where(miss, 0, idx)
    vals = col.values[safe]
    mask = None if col.mask is None else col.mask[safe].copy()
    if mask is not None:
        mask[miss] = False
    elif col.values.dtype == object:
        vals = vals.copy()
        empty = col.ftype.empty_value()
        for i in np.nonzero(miss)[0]:
            vals[i] = empty
    else:  # vector kinds: zero rows
        vals = vals.copy()
        vals[miss] = 0
    return HostColumn(col.ftype, vals, mask, col.meta)


class JoinedDataReader(DataReader):
    """Joins two readers' frames. Itself a reader, so joins chain
    (reference ``JoinedReader`` composing further joins)."""

    def __init__(self, left: DataReader, right: DataReader,
                 join_keys: JoinKeys = JoinKeys(),
                 join_type: str = "left-outer"):
        super().__init__(key_fn=None)
        if join_type not in ("left-outer", "inner"):
            raise ValueError(f"join_type {join_type!r}; use left-outer|inner")
        self.left, self.right = left, right
        self.join_keys = join_keys
        self.join_type = join_type

    # chaining sugar (reference reader.leftOuterJoin/innerJoin)
    def left_outer_join(self, other: DataReader,
                        join_keys: JoinKeys = JoinKeys()) -> "JoinedDataReader":
        return JoinedDataReader(self, other, join_keys, "left-outer")

    def inner_join(self, other: DataReader,
                   join_keys: JoinKeys = JoinKeys()) -> "JoinedDataReader":
        return JoinedDataReader(self, other, join_keys, "inner")

    def with_secondary_aggregation(
            self, time_filter: TimeBasedFilter) -> "JoinedAggregateDataReader":
        return JoinedAggregateDataReader(self, time_filter)

    def available_columns(self) -> Optional[set]:
        l, r = self.left.available_columns(), self.right.available_columns()
        if l is None or r is None:
            return None
        return l | r

    def read(self) -> Iterable[Any]:
        raise NotImplementedError(
            "JoinedDataReader produces frames, not records")

    # -- feature partitioning ------------------------------------------------
    @staticmethod
    def _has_tag(reader: DataReader, tag: str) -> bool:
        """Does this reader (or any side of a nested join, or a grouping
        wrapper's base) carry the source tag?"""
        if getattr(reader, "source_tag", None) == tag:
            return True
        for attr in ("left", "right", "base", "joined"):
            sub = getattr(reader, attr, None)
            if sub is not None and JoinedDataReader._has_tag(sub, tag):
                return True
        return False

    def _split_features(self, raw_features: Sequence[FeatureLike]
                        ) -> tuple[list[FeatureLike], list[FeatureLike]]:
        lcols = self.left.available_columns()
        rcols = self.right.available_columns()
        lf, rf = [], []
        for f in raw_features:
            # explicit binding first (reference: features bind to a reader
            # via the record type; extracted features aren't columns)
            tag = getattr(f.origin_stage, "source_tag", None)
            if tag is not None:
                if self._has_tag(self.left, tag):
                    lf.append(f)
                    continue
                if self._has_tag(self.right, tag):
                    rf.append(f)
                    continue
                raise KeyError(
                    f"raw feature {f.name!r} is bound to source tag "
                    f"{tag!r}, which neither side of the join carries")
            in_l = lcols is None or f.name in lcols
            in_r = rcols is not None and f.name in rcols
            if in_r and (not in_l or lcols is None):
                rf.append(f)
            elif in_l:
                lf.append(f)
            else:
                raise KeyError(
                    f"raw feature {f.name!r} not found in either side of "
                    "join (name not a column of either reader and no "
                    ".source(tag) binding)")
        return lf, rf

    # -- the join ------------------------------------------------------------
    def _joined_indexed(self, raw_features: Sequence[FeatureLike]
                        ) -> tuple[HostFrame, list[str], list[str],
                                   np.ndarray, np.ndarray]:
        """Returns (joined frame, left names, right names, left row index
        per output row, right row index per output row; -1 = unmatched)."""
        lf, rf = self._split_features(raw_features)
        lframe = self.left.generate_frame(lf)
        rframe = self.right.generate_frame(rf)
        lkeys = _key_strings(lframe, self.join_keys.left_key)
        rkeys = _key_strings(rframe, self.join_keys.right_key)

        rindex: dict[str, list[int]] = defaultdict(list)
        for j, k in enumerate(rkeys):
            if k is not None:
                rindex[k].append(j)

        lidx: list[int] = []
        ridx: list[int] = []
        for i, k in enumerate(lkeys):
            matches = rindex.get(k, []) if k is not None else []
            if matches:
                for j in matches:
                    lidx.append(i)
                    ridx.append(j)
            elif self.join_type == "left-outer":
                lidx.append(i)
                ridx.append(-1)
        li = np.asarray(lidx, dtype=np.int64)
        ri = np.asarray(ridx, dtype=np.int64)

        cols: dict[str, HostColumn] = {}
        for name, col in lframe.columns.items():
            cols[name] = col.take(li)
        for name, col in rframe.columns.items():
            if name in cols:
                raise ValueError(f"duplicate column {name!r} across join sides")
            cols[name] = _take_with_null(col, ri)
        key = lkeys[li] if len(li) else np.asarray([], dtype=object)
        frame = HostFrame(cols, key)
        return frame, [f.name for f in lf], [f.name for f in rf], li, ri

    def generate_frame(self, raw_features: Sequence[FeatureLike]) -> HostFrame:
        frame, _, _, _, _ = self._joined_indexed(raw_features)
        return frame


class JoinedAggregateDataReader(DataReader):
    """Join then re-aggregate the right side per result key
    (reference ``JoinedAggregateDataReader.aggregateRightData``)."""

    def __init__(self, joined: JoinedDataReader, time_filter: TimeBasedFilter):
        super().__init__(key_fn=None)
        self.joined = joined
        self.time_filter = time_filter

    def available_columns(self) -> Optional[set]:
        return self.joined.available_columns()

    def read(self) -> Iterable[Any]:
        raise NotImplementedError(
            "JoinedAggregateDataReader produces frames, not records")

    def generate_frame(self, raw_features: Sequence[FeatureLike]) -> HostFrame:
        frame, lnames, rnames, li, ri = self.joined._joined_indexed(raw_features)
        tf = self.time_filter
        by_f = {f.name: f for f in raw_features}
        cond = frame[tf.condition]
        if tf.primary not in frame:
            raise KeyError(
                f"TimeBasedFilter.primary {tf.primary!r} is not among the "
                "requested raw features; the time filter would be inert")
        prim = frame[tf.primary]

        # Group joined rows by *left row* (not by key): duplicate left keys
        # stay distinct output rows and each right match is counted once.
        groups: dict[int, list[int]] = {}
        order: list[int] = []
        for i, lrow in enumerate(li):
            lrow = int(lrow)
            if lrow not in groups:
                order.append(lrow)
            groups.setdefault(lrow, []).append(i)

        keys: list[str] = []
        cols: dict[str, list[Any]] = {n: [] for n in frame.names()}
        for lrow in order:
            rows = groups[lrow]
            first = rows[0]
            keys.append(str(frame.key[first]))
            cutoff = cond.python_value(first)
            for name in lnames:
                cols[name].append(frame[name].python_value(first))
            for name in rnames:
                f = by_f[name]
                col = frame[name]
                agg = FeatureAggregator(
                    aggregator_of(f.ftype), is_response=f.is_response,
                    window_ms=tf.window_ms)
                events = []
                for i in rows:
                    if ri[i] < 0:
                        continue  # unmatched left row: no right events
                    v = col.python_value(i)
                    t = prim.python_value(i)
                    events.append(Event(int(t) if t is not None else 0, v))
                events.sort(key=lambda e: e.time)
                cut = int(cutoff) if cutoff is not None else None
                cols[name].append(agg.extract(events, cut))
        host_cols = {
            n: HostColumn.from_values(frame[n].ftype, cols[n])
            for n in frame.names()}
        return HostFrame(host_cols, np.asarray(keys, dtype=object))
