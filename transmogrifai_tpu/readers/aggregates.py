"""Aggregate & conditional readers: time-series event -> entity rollup.

Parity: reference ``readers/AggregateDataReaders.scala`` /
``ConditionalDataReaders.scala`` + ``DataReader.scala:216-260``
(AggregatedReader): group records by entity key, then reduce each feature's
events with its monoid aggregator honoring a cutoff:

- **AggregateDataReader**: one global ``cutoff_ms``; predictors aggregate
  events at/before it, responses after it.
- **ConditionalDataReader**: per-key cutoff = time of the first event
  matching ``condition_fn``; keys with no matching event are dropped.

TPU note (SURVEY §2.7): the reference's groupByKey shuffle becomes a
host-side stable sort over keys; the per-group monoid reduction happens at
ingest (string/object-typed), so there is nothing to put on device here.
"""

from __future__ import annotations

from collections import defaultdict
from typing import Any, Callable, Iterable, Optional, Sequence

import numpy as np

from transmogrifai_tpu.aggregators.monoid import (
    Event, FeatureAggregator, aggregator_of,
)
from transmogrifai_tpu.features.feature import FeatureLike
from transmogrifai_tpu.frame import HostColumn, HostFrame
from transmogrifai_tpu.readers.base import DataReader

__all__ = ["AggregateDataReader", "ConditionalDataReader"]


class _GroupingReader(DataReader):
    def __init__(self, base: DataReader,
                 key_fn: Callable[[Any], str],
                 time_fn: Callable[[Any], int]):
        super().__init__(key_fn=key_fn)
        self.base = base
        self.time_fn = time_fn

    def read(self) -> Iterable[Any]:
        return self.base.read()

    def available_columns(self):
        return self.base.available_columns()

    def _groups(self) -> dict[str, list[tuple[int, Any]]]:
        groups: dict[str, list[tuple[int, Any]]] = defaultdict(list)
        for r in self.base.read():
            groups[str(self.key_fn(r))].append((int(self.time_fn(r)), r))
        for events in groups.values():
            events.sort(key=lambda tr: tr[0])
        return groups

    def _aggregate_groups(self, raw_features: Sequence[FeatureLike],
                          groups: dict[str, list[tuple[int, Any]]],
                          cutoff_of: Callable[[str], Optional[int]]
                          ) -> HostFrame:
        keys = sorted(groups)
        aggs = []
        for f in raw_features:
            stage = f.origin_stage
            agg = stage.aggregator or aggregator_of(f.ftype)
            aggs.append(FeatureAggregator(
                agg, is_response=f.is_response,
                window_ms=getattr(stage, "window_ms", None)))
        cols: dict[str, list[Any]] = {f.name: [] for f in raw_features}
        for k in keys:
            cutoff = cutoff_of(k)
            events = groups[k]
            for f, fa in zip(raw_features, aggs):
                stage = f.origin_stage
                evs = [Event(t, stage.extract(r)) for t, r in events]
                cols[f.name].append(fa.extract(evs, cutoff))
        host_cols = {f.name: HostColumn.from_values(f.ftype, cols[f.name])
                     for f in raw_features}
        return HostFrame(host_cols, np.asarray(keys, dtype=object))


class AggregateDataReader(_GroupingReader):
    """Aggregate all of an entity's events up to a global cutoff time."""

    def __init__(self, base: DataReader,
                 key_fn: Callable[[Any], str],
                 time_fn: Callable[[Any], int],
                 cutoff_ms: Optional[int] = None):
        super().__init__(base, key_fn, time_fn)
        self.cutoff_ms = cutoff_ms

    def generate_frame(self, raw_features: Sequence[FeatureLike]) -> HostFrame:
        groups = self._groups()
        return self._aggregate_groups(
            raw_features, groups, lambda _k: self.cutoff_ms)


class ConditionalDataReader(_GroupingReader):
    """Per-key cutoff from the first event matching ``condition_fn``;
    response aggregates after the condition event, predictors before."""

    def __init__(self, base: DataReader,
                 key_fn: Callable[[Any], str],
                 time_fn: Callable[[Any], int],
                 condition_fn: Callable[[Any], bool],
                 drop_if_no_condition: bool = True):
        super().__init__(base, key_fn, time_fn)
        self.condition_fn = condition_fn
        self.drop_if_no_condition = drop_if_no_condition

    def generate_frame(self, raw_features: Sequence[FeatureLike]) -> HostFrame:
        groups = self._groups()
        cutoffs: dict[str, Optional[int]] = {}
        for k, events in groups.items():
            cut = None
            for t, r in events:
                if self.condition_fn(r):
                    cut = t
                    break
            cutoffs[k] = cut
        if self.drop_if_no_condition:
            groups = {k: v for k, v in groups.items() if cutoffs[k] is not None}
        return self._aggregate_groups(raw_features, groups,
                                      lambda k: cutoffs[k])
