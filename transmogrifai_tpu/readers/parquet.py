"""Parquet reader: columnar files -> raw-feature HostFrame.

Parity: reference ``readers/DataReaders.scala`` parquetProduct/parquetCase
variants (Spark's parquet source). Here ingestion is pyarrow -> numpy
columns; schema inference maps arrow types onto the feature-type system the
same way the CSV auto-reader infers from strings.
"""

from __future__ import annotations

from typing import Any, Iterable, Optional

from transmogrifai_tpu.readers.base import DataReader
from transmogrifai_tpu.types import feature_types as ft

__all__ = ["ParquetReader", "feature_schema_of_arrow"]


def _pyarrow():
    try:
        import pyarrow  # noqa: F401
        import pyarrow.parquet as pq
        return pq
    except ImportError as e:  # pragma: no cover - environment-dependent
        raise ImportError(
            "ParquetReader requires pyarrow; install it or use the CSV/Avro "
            "readers") from e


def feature_schema_of_arrow(schema) -> dict[str, type[ft.FeatureType]]:
    """Arrow schema -> {column: FeatureType}."""
    import pyarrow as pa

    out: dict[str, type[ft.FeatureType]] = {}
    for field in schema:
        t = field.type
        if pa.types.is_boolean(t):
            fty: type[ft.FeatureType] = ft.Binary
        elif pa.types.is_integer(t):
            fty = ft.Integral
        elif pa.types.is_floating(t) or pa.types.is_decimal(t):
            fty = ft.Real
        elif pa.types.is_timestamp(t) or pa.types.is_date(t):
            fty = ft.DateTime
        elif (pa.types.is_list(t) or pa.types.is_large_list(t)) and (
                pa.types.is_string(t.value_type)
                or pa.types.is_large_string(t.value_type)):
            fty = ft.TextList
        elif pa.types.is_map(t) or pa.types.is_struct(t):
            fty = ft.TextMap
        else:
            fty = ft.Text
        out[field.name] = fty
    return out


class ParquetReader(DataReader):
    """Reads one parquet file (or dataset directory) into records."""

    def __init__(self, path: str,
                 schema: Optional[dict[str, type[ft.FeatureType]]] = None,
                 key_col: Optional[str] = None,
                 columns: Optional[list[str]] = None):
        self.path = path
        self._schema = schema
        self.key_col = key_col
        self.columns = columns
        super().__init__(
            key_fn=(lambda r: str(r[key_col])) if key_col else None)

    def _table(self):
        pq = _pyarrow()
        return pq.read_table(self.path, columns=self.columns)

    def schema(self) -> dict[str, type[ft.FeatureType]]:
        if self._schema is None:
            # metadata-only read: no data materialization for schema probes
            arrow = _pyarrow().read_schema(self.path)
            if self.columns is not None:
                keep = set(self.columns)
                arrow = [f for f in arrow if f.name in keep]
            self._schema = feature_schema_of_arrow(arrow)
        return self._schema

    def available_columns(self):
        return set(self.schema())

    def read(self) -> Iterable[dict[str, Any]]:
        schema = self.schema()
        table = self._table()
        for batch in table.to_batches():
            rows = batch.to_pylist()
            for r in rows:
                yield {k: _coerce(v, schema.get(k)) for k, v in r.items()}


def _coerce(v: Any, fty: Optional[type[ft.FeatureType]]) -> Any:
    if v is None:
        return None
    if fty is not None and issubclass(fty, (ft.Date, ft.DateTime)):
        import calendar
        import datetime
        if isinstance(v, datetime.datetime):
            if v.tzinfo is None:
                # naive parquet timestamps are UTC by convention; never let
                # the host timezone shift feature values between machines
                v = v.replace(tzinfo=datetime.timezone.utc)
            return int(v.timestamp() * 1000)
        if isinstance(v, datetime.date):
            return int(calendar.timegm((v.year, v.month, v.day, 0, 0, 0))
                       * 1000)
    if fty is not None and issubclass(fty, ft.Text) and not isinstance(v, str):
        return str(v)
    return v
