"""Avro reader: container files -> records -> raw-feature HostFrame.

Parity: reference ``readers/DataReaders.scala`` avro variants +
``utils/io/avro/AvroInOut.scala`` + ``FeatureBuilder.fromSchema`` (Avro
schema -> typed features). Uses the pure-Python container codec in
``utils/avro_io`` (deflate/snappy/null).
"""

from __future__ import annotations

from typing import Any, Iterable, Optional

from transmogrifai_tpu.readers.base import DataReader
from transmogrifai_tpu.types import feature_types as ft
from transmogrifai_tpu.utils.avro_io import iter_avro, read_avro_schema

__all__ = ["AvroReader", "feature_schema_of_avro", "save_avro"]


def save_avro(frame, path: str, name: str = "Row",
              codec: str = "deflate") -> None:
    """Save a HostFrame as an Avro container file (reference
    ``RichDataset.saveAvro``). The entity key, when present, is written as a
    ``key`` column."""
    from transmogrifai_tpu.utils.avro_io import (
        avro_schema_of_records, plain_value, write_avro,
    )
    records = []
    for i in range(frame.n_rows):
        rec = {k: plain_value(v) for k, v in frame.row(i).items()}
        if frame.key is not None:
            rec.setdefault("key", str(frame.key[i]))
        records.append(rec)
    schema = avro_schema_of_records(records, name=name)
    write_avro(path, schema, records, codec=codec)


def _branch_types(t: Any) -> list:
    """Union -> non-null branches; plain type -> [type]."""
    if isinstance(t, list):
        return [b for b in t if b != "null"]
    return [t]


def feature_schema_of_avro(avro_schema: dict) -> dict[str, type[ft.FeatureType]]:
    """Map an Avro record schema to feature types (reference
    ``FeatureBuilder.fromSchema``: int/long -> Integral, float/double -> Real,
    boolean -> Binary, string/enum -> Text, map[string] -> TextMap,
    map[numeric] -> RealMap, array[string] -> TextList)."""
    if avro_schema.get("type") != "record":
        raise ValueError("expected an Avro record schema")
    out: dict[str, type[ft.FeatureType]] = {}
    for f in avro_schema["fields"]:
        branches = _branch_types(f["type"])
        t = branches[0] if branches else "null"
        name = t if isinstance(t, str) else t.get("type")
        if name in ("int", "long"):
            fty: type[ft.FeatureType] = ft.Integral
        elif name in ("float", "double"):
            fty = ft.Real
        elif name == "boolean":
            fty = ft.Binary
        elif name in ("string", "enum", "bytes", "fixed"):
            fty = ft.Text
        elif name == "map":
            vt = _branch_types(t["values"])
            vname = vt[0] if isinstance(vt[0], str) else vt[0].get("type")
            if vname in ("int", "long", "float", "double"):
                fty = ft.RealMap
            elif vname == "boolean":
                fty = ft.BinaryMap
            else:
                fty = ft.TextMap
        elif name == "array":
            fty = ft.TextList
        else:  # nested records etc. -> opaque text
            fty = ft.Text
        out[f["name"]] = fty
    return out


class AvroReader(DataReader):
    """Reads Avro container files; one record dict per row."""

    def __init__(self, path: str,
                 schema: Optional[dict[str, type[ft.FeatureType]]] = None,
                 key_col: Optional[str] = None):
        super().__init__(
            key_fn=(lambda r: str(r[key_col])) if key_col else None)
        self.path = path
        self._schema = schema
        self._avro_schema: Optional[dict] = None

    @property
    def avro_schema(self) -> dict:
        if self._avro_schema is None:
            self._avro_schema = read_avro_schema(self.path)
        return self._avro_schema

    def schema(self) -> dict[str, type[ft.FeatureType]]:
        """Feature-type schema: explicit if given, else inferred from the
        file's Avro schema."""
        if self._schema is None:
            self._schema = feature_schema_of_avro(self.avro_schema)
        return self._schema

    def available_columns(self):
        return set(self.schema())

    def read(self) -> Iterable[dict[str, Any]]:
        sch = self.schema()
        for rec in iter_avro(self.path):
            yield {k: _coerce(v, sch.get(k)) for k, v in rec.items()}


def _coerce(v: Any, fty: Optional[type[ft.FeatureType]]) -> Any:
    if v is None or fty is None:
        return v
    if fty is ft.Real and isinstance(v, int):
        return float(v)
    if isinstance(v, bytes):
        return v.decode("utf-8", "replace")
    return v
