"""Workflow runner: CLI-style train/score/evaluate entry point.

Parity: reference ``core/.../OpWorkflowRunner.scala`` / ``OpApp.scala`` —
run types Train / Score / Evaluate / Features driven by an OpParams json,
writing model/metrics/scores to configured locations and reporting a result
json; `python -m transmogrifai_tpu.runner --run-type train --params p.json`
mirrors the spark-submit surface.
"""

from __future__ import annotations

import os
import argparse
import json
import sys
import time
from typing import Any, Callable, Optional

from transmogrifai_tpu.params import OpParams
from transmogrifai_tpu.utils.profiling import OpStep, profiler
from transmogrifai_tpu.workflow import Workflow, WorkflowModel, load_model

__all__ = ["WorkflowRunner", "RunTypes"]


class RunTypes:
    TRAIN = "train"
    SCORE = "score"
    STREAMING_SCORE = "streaming-score"
    SERVE = "serve"
    SCALEOUT = "scaleout"
    CONTINUOUS = "continuous"
    EVALUATE = "evaluate"
    FEATURES = "features"
    ALL = (TRAIN, SCORE, STREAMING_SCORE, SERVE, SCALEOUT, CONTINUOUS,
           EVALUATE, FEATURES)


class WorkflowRunner:
    """Wraps a workflow + evaluator + reader factory for parameterized runs."""

    def __init__(self, workflow: Workflow,
                 evaluator=None,
                 scoring_reader_factory: Optional[Callable[[OpParams], Any]] = None):
        self.workflow = workflow
        self.evaluator = evaluator
        self.scoring_reader_factory = scoring_reader_factory
        self.on_end_handlers: list[Callable[[dict], None]] = []

    def run(self, run_type: str, params: OpParams,
            checkpoint_dir: Optional[str] = None,
            trace_out: Optional[str] = None) -> dict:
        """Execute one parameterized run. ``checkpoint_dir`` (TRAIN only)
        enables resumable training: fitted DAG layers and the selector
        sweep checkpoint there, and re-running the same command after a
        crash/preemption resumes instead of refitting (the run result's
        ``appMetrics.runCounters.layersResumed`` reports how much work the
        resume skipped). ``trace_out`` profiles the whole run (one
        ``jax.profiler`` trace when the backend supports it) and writes a
        Perfetto/chrome://tracing JSON merging the host span tree with the
        device timeline there (docs/OBSERVABILITY.md)."""
        t0 = time.time()
        trace_dir = None
        if trace_out:
            import tempfile
            trace_dir = tempfile.mkdtemp(prefix="transmogrifai_trace_")
        profiler.reset(app_name=f"transmogrifai_tpu.{run_type}",
                       trace_dir=trace_dir)
        applied = params.apply_to_stages(
            [s for f in self.workflow.result_features
             for s in f.parent_stages()])
        reader_applied = params.apply_to_reader(self.workflow.reader)
        #: custom params ride on the workflow for app/stage code (reference
        #: OpParams.customParams passthrough)
        self.workflow.op_params = params
        result: dict = {"runType": run_type, "stageOverrides": applied,
                        "readerOverrides": reader_applied}
        if params.custom_params:
            result["customParams"] = dict(params.custom_params)
        try:
            if run_type == RunTypes.TRAIN:
                with profiler.phase(OpStep.MODEL_TRAINING):
                    model = self.workflow.train(
                        checkpoint_dir=checkpoint_dir)
                if checkpoint_dir:
                    result["checkpointDir"] = checkpoint_dir
                if params.model_location:
                    with profiler.phase(OpStep.RESULTS_SAVING):
                        model.save(params.model_location)
                    result["modelLocation"] = params.model_location
                result["summary"] = model.summary_json()
            elif run_type == RunTypes.STREAMING_SCORE:
                # reference OpWorkflowRunner StreamingScore: score every
                # micro-batch as it lands, writing per-batch score files
                from transmogrifai_tpu.readers.streaming import (
                    StreamingReader, stream_score,
                )
                if params.model_location is None:
                    raise ValueError(f"{run_type} requires modelLocation")
                model = load_model(params.model_location)
                reader = (self.scoring_reader_factory(params)
                          if self.scoring_reader_factory
                          else self.workflow.reader)
                if not isinstance(reader, StreamingReader):
                    raise ValueError(
                        "streaming-score requires a StreamingReader (got "
                        f"{type(reader).__name__})")

                def write_batch(frame, i):
                    if not params.score_location:
                        return
                    from transmogrifai_tpu.readers.avro import save_avro
                    os.makedirs(params.score_location, exist_ok=True)
                    # idempotent per-source naming: a checkpoint-resumed
                    # stream that REPLAYS the in-flight batch overwrites
                    # the same score file instead of duplicating rows;
                    # non-file sources fall back to the stream index
                    src = getattr(reader, "current_file", None)
                    if src:
                        import hashlib
                        # short path hash: distinct sources sharing a
                        # basename stem (day1.csv vs day1.avro, same-named
                        # files in sibling dirs) must not collide
                        tag = hashlib.sha1(
                            src.encode()).hexdigest()[:8]
                        stem = (os.path.splitext(os.path.basename(src))[0]
                                + "_" + tag)
                    else:
                        stem = f"batch_{i:06d}"
                    out = os.path.join(params.score_location,
                                       f"scores_{stem}.avro")
                    tmp = out + ".tmp"
                    save_avro(frame, tmp)   # atomic: no truncated .avro
                    os.replace(tmp, out)    # survives a crash mid-write

                n_rows = n_batches = 0
                with profiler.phase(OpStep.SCORING):
                    for frame in stream_score(model, reader, write_batch):
                        n_batches += 1
                        n_rows += frame.n_rows
                result["nBatches"] = n_batches
                result["nRows"] = n_rows
            elif run_type == RunTypes.CONTINUOUS:
                # closed-loop continuous AutoML: stream ingest + drift
                # detection + checkpoint-resumed retrain + zero-downtime
                # hot-swap, one long-running supervised process
                # (docs/CONTINUOUS.md). The runner's workflow is the
                # retrain template; customParams.streamDir names the
                # watched directory and checkpoint_dir (or
                # customParams.stateDir) the durable resume root.
                self._run_continuous(params, result, checkpoint_dir)
            elif run_type == RunTypes.SCALEOUT:
                # multi-process serving scale-out replay: spin the
                # router + N replica worker subprocesses and drive the
                # reader's rows through the HTTP front (docs/SERVING.md
                # "Scale-out"). customParams: modelDir (required),
                # replicas, defaultModel (replay target), stateDir
                # (default --checkpoint-dir)
                self._run_scaleout(params, result, checkpoint_dir)
            elif run_type == RunTypes.SERVE and \
                    (params.custom_params or {}).get("modelDir"):
                # fleet replay: customParams.modelDir registers every
                # saved model under a directory into a FleetServer and
                # replays the reader against customParams.defaultModel
                # (docs/SERVING.md "Serving fleet")
                self._serve_fleet(params, result)
            elif run_type == RunTypes.SERVE:
                # online-serving replay: every reader row becomes one
                # submit() through the micro-batched server (admission,
                # batching, degradation all exercised), metrics reported
                # in the result json (see docs/SERVING.md)
                if params.model_location is None:
                    raise ValueError(f"{run_type} requires modelLocation")
                from transmogrifai_tpu.serving import ScoringServer
                model = load_model(params.model_location)
                reader = (self.scoring_reader_factory(params)
                          if self.scoring_reader_factory
                          else self.workflow.reader)
                # requests carry predictors only — the online contract
                predictors = [f for f in model.raw_features
                              if not f.is_response]
                frame = reader.generate_frame(predictors)
                cp = dict(params.custom_params or {})
                timeout_ms = cp.get("timeoutMs")
                queue_capacity = int(cp.get("queueCapacity", 1024))
                server = ScoringServer(
                    model,
                    max_batch=int(cp.get("maxBatch", 256)),
                    max_wait_ms=float(cp.get("maxWaitMs", 2.0)),
                    queue_capacity=queue_capacity,
                    default_timeout_ms=(float(timeout_ms)
                                        if timeout_ms is not None else None),
                    strict=bool(cp.get("strict", True)),
                    retries=int(cp.get("retries", 2)))
                out_fh = out_path = tmp = None
                if params.score_location:
                    os.makedirs(params.score_location, exist_ok=True)
                    out_path = os.path.join(params.score_location,
                                            "scores_serve.jsonl")
                    tmp = out_path + ".tmp"
                    out_fh = open(tmp, "w")
                n_rows = n_errors = 0
                window: list = []

                def _drain_window() -> None:
                    # a failed/expired request reports in ITS slot; it
                    # must not discard the rest of the replay. Draining
                    # per queue_capacity window keeps memory bounded —
                    # the admission queue's bound means nothing if the
                    # replay holds every row/future/score at once
                    nonlocal n_rows, n_errors
                    for f in window:
                        try:
                            s = f.result()
                        except Exception as e:  # noqa: BLE001 — reported in the result slot
                            s = {"error": f"{type(e).__name__}: {e}"}
                            n_errors += 1
                        n_rows += 1
                        if out_fh is not None:
                            out_fh.write(json.dumps(s, default=str) + "\n")
                    window.clear()

                with profiler.phase(OpStep.SCORING):
                    row_iter = frame.iter_rows()
                    first = next(row_iter, None)
                    server.start(warmup_row=first)
                    try:
                        if first is not None:
                            import itertools
                            for row in itertools.chain([first], row_iter):
                                window.append(server.submit_blocking(row))
                                if len(window) >= queue_capacity:
                                    _drain_window()
                        _drain_window()
                    finally:
                        server.stop()
                if out_fh is not None:
                    out_fh.close()
                    os.replace(tmp, out_path)
                    result["scoreLocation"] = out_path
                result["nRows"] = n_rows
                result["nErrors"] = n_errors
                # the replay is already inside a SCORING phase: don't let
                # the snapshot mirror the dispatch wall in a second time
                result["servingMetrics"] = server.snapshot(
                    mirror_to_profiler=False)
            elif run_type in (RunTypes.SCORE, RunTypes.EVALUATE,
                              RunTypes.FEATURES):
                if params.model_location is None:
                    raise ValueError(f"{run_type} requires modelLocation")
                model = load_model(params.model_location)
                reader = (self.scoring_reader_factory(params)
                          if self.scoring_reader_factory
                          else self.workflow.reader)
                if run_type == RunTypes.FEATURES:
                    with profiler.phase(OpStep.FEATURE_ENGINEERING):
                        frame = model.score(reader, keep_raw_features=True,
                                            keep_intermediate_features=True)
                    result["nRows"] = frame.n_rows
                    result["columns"] = frame.names()
                else:
                    with profiler.phase(OpStep.SCORING):
                        scores = model.score(reader)
                    result["nRows"] = scores.n_rows
                    if params.score_location:
                        # reference OpWorkflowRunner writes scores to the
                        # configured location. scoreLocation is a DIRECTORY
                        # in every run type (streaming writes batch files
                        # into it; score writes scores.avro) — one param,
                        # one meaning
                        with profiler.phase(OpStep.RESULTS_SAVING):
                            from transmogrifai_tpu.readers.avro import (
                                save_avro,
                            )
                            os.makedirs(params.score_location, exist_ok=True)
                            out_path = os.path.join(params.score_location,
                                                    "scores.avro")
                            save_avro(scores, out_path)
                        result["scoreLocation"] = out_path
                    if run_type == RunTypes.EVALUATE:
                        if self.evaluator is None:
                            raise ValueError("evaluate requires an evaluator")
                        with profiler.phase(OpStep.EVALUATION):
                            metrics = model.evaluate(reader, self.evaluator)
                        from transmogrifai_tpu.evaluators.base import EvaluatorBase
                        result["metrics"] = EvaluatorBase.to_json(metrics)
                        if params.metrics_location:
                            with open(params.metrics_location, "w") as fh:
                                json.dump(result["metrics"], fh, indent=2)
            else:
                raise ValueError(
                    f"Unknown run type {run_type!r}; one of {RunTypes.ALL}")
            result["status"] = "success"
        except Exception as e:  # report failure like the reference runner
            result["status"] = "failure"
            result["error"] = f"{type(e).__name__}: {e}"
            raise
        finally:
            result["wallSeconds"] = time.time() - t0
            metrics = profiler.finalize()
            if trace_out:
                try:
                    result["trace"] = metrics.export_chrome_trace(trace_out)
                    result["traceOut"] = trace_out
                except Exception as e:  # noqa: BLE001 — a failed trace export must not fail the run
                    result["traceError"] = f"{type(e).__name__}: {e}"
            if trace_dir:
                import shutil
                # the XSpace protos are parsed at finalize(); only the
                # merged chrome trace is the artifact — repeated profiled
                # runs must not accumulate proto dirs in /tmp
                shutil.rmtree(trace_dir, ignore_errors=True)
            result["appMetrics"] = metrics.to_json()
            # host-pressure snapshot at run end (utils/resources.py):
            # pairs with appMetrics.resourceCounters so a result json
            # shows both WHAT rungs the run took and the pressure state
            # it finished under
            from transmogrifai_tpu.utils.resources import pressure_state
            result["resourcePressure"] = pressure_state(
                checkpoint_dir or ".")
            for h in self.on_end_handlers:
                h(result)
        return result

    def _run_continuous(self, params: OpParams, result: dict,
                        checkpoint_dir: Optional[str]) -> None:
        """CONTINUOUS: drive a ``continuous.ContinuousLoop`` from
        OpParams. ``customParams``: ``streamDir`` (required), ``pattern``,
        ``stateDir`` (default: ``--checkpoint-dir``), ``modelId``,
        ``windowBatches``, ``maxBufferBatches``, ``maxWindows``,
        ``timeoutS``, ``pollIntervalS``, drift knobs (``driftMetric``,
        ``jsThreshold``, ``psiThreshold``, ``fillDeltaThreshold``,
        ``labelDeltaThreshold``, ``consecutiveWindows``,
        ``cooldownWindows``), ``shadowTolerance``, ``stalenessBoundS``,
        ``metricsPort``, ``accessLogSample`` (sampled http.access
        events), ``sloConfig`` (objectives JSON path), ``eventsSpill``
        (durable flight-recorder JSONL under the state dir, default
        on). ``modelLocation`` loads the initial serving
        model; without it the loop bootstraps from the first window.
        ``referencePath`` names a batch file sampling that model's
        training data to pin the drift reference (else the first stream
        window is adopted)."""
        from transmogrifai_tpu.continuous import ContinuousLoop, DriftConfig
        cp = dict(params.custom_params or {})
        stream_dir = cp.get("streamDir")
        if not stream_dir:
            raise ValueError("continuous requires customParams.streamDir")
        state_dir = cp.get("stateDir") or checkpoint_dir
        if not state_dir:
            raise ValueError(
                "continuous requires a durable state root: pass "
                "--checkpoint-dir or customParams.stateDir")
        initial_model = (load_model(params.model_location)
                         if params.model_location else None)
        drift = DriftConfig(
            metric=cp.get("driftMetric", "js"),
            js_threshold=float(cp.get("jsThreshold", 0.25)),
            psi_threshold=float(cp.get("psiThreshold", 0.25)),
            fill_delta_threshold=float(cp.get("fillDeltaThreshold", 0.25)),
            label_delta_threshold=float(cp.get("labelDeltaThreshold",
                                               0.25)),
            consecutive_windows=int(cp.get("consecutiveWindows", 2)),
            cooldown_windows=int(cp.get("cooldownWindows", 2)))
        loop = ContinuousLoop(
            self.workflow, stream_dir, state_dir,
            model_id=cp.get("modelId", "live"),
            pattern=cp.get("pattern", "*"),
            initial_model=initial_model,
            reference_path=cp.get("referencePath"),
            drift=drift,
            window_batches=int(cp.get("windowBatches", 4)),
            max_buffer_batches=int(cp.get("maxBufferBatches", 8)),
            poll_interval_s=float(cp.get("pollIntervalS", 1.0)),
            timeout_s=(float(cp["timeoutS"]) if "timeoutS" in cp
                       else None),
            max_windows=(int(cp["maxWindows"]) if "maxWindows" in cp
                         else None),
            max_retrain_attempts=int(cp.get("maxRetrainAttempts", 3)),
            shadow_tolerance=float(cp.get("shadowTolerance", 1.0)),
            staleness_bound_s=(float(cp["stalenessBoundS"])
                               if "stalenessBoundS" in cp else None),
            metrics_port=(int(cp["metricsPort"]) if "metricsPort" in cp
                          else None),
            access_log_sample=float(cp.get("accessLogSample", 0.0)),
            slo=cp.get("sloConfig"),
            events_spill=bool(cp.get("eventsSpill", True)))
        result["continuous"] = loop.run()
        result["stateDir"] = state_dir

    def _run_scaleout(self, params: OpParams, result: dict,
                      checkpoint_dir: Optional[str]) -> None:
        """SCALEOUT: replay the reader's rows through a live
        router + replica-worker stack over HTTP — every row takes the
        full multi-process path (router hash/spill, replica admission,
        micro-batched compiled scoring). The reader materializes ONE
        model's predictor columns, so ``customParams.defaultModel``
        names the replay target when more than one model is
        registered (same contract as the SERVE fleet replay)."""
        import http.client

        from transmogrifai_tpu.scaleout.stack import ScaleoutStack
        cp = dict(params.custom_params or {})
        model_dir = cp.get("modelDir")
        if not model_dir:
            raise ValueError("scaleout requires customParams.modelDir")
        state_dir = cp.get("stateDir") or checkpoint_dir
        if not state_dir:
            raise ValueError("scaleout requires a state root: pass "
                             "--checkpoint-dir or customParams.stateDir")
        stack = ScaleoutStack(
            model_dir, state_dir,
            replicas=int(cp.get("replicas", 2)),
            spill=int(cp.get("spill", 2)),
            worker_args=["--max-batch", str(cp.get("maxBatch", 64)),
                         "--queue-capacity",
                         str(cp.get("queueCapacity", 256))])
        ids = sorted(
            d for d in os.listdir(model_dir)
            if os.path.isdir(os.path.join(model_dir, d))
            and not d.startswith("_"))
        target = cp.get("defaultModel") or \
            (ids[0] if len(ids) == 1 else None)
        if target is None:
            raise ValueError(
                f"modelDir holds {len(ids)} models ({', '.join(ids)}); "
                "customParams.defaultModel must name the replay target")
        from transmogrifai_tpu.workflow import load_model
        from transmogrifai_tpu.serialization import MODEL_JSON
        tdir = os.path.join(model_dir, target)
        if not os.path.exists(os.path.join(tdir, MODEL_JSON)):
            versions = sorted(v for v in os.listdir(tdir)
                              if os.path.exists(os.path.join(
                                  tdir, v, MODEL_JSON)))
            if not versions:
                raise ValueError(f"no saved model under {tdir!r}")
            tdir = os.path.join(tdir, versions[0])
        ref = load_model(tdir)
        reader = (self.scoring_reader_factory(params)
                  if self.scoring_reader_factory else self.workflow.reader)
        predictors = [f for f in ref.raw_features if not f.is_response]
        frame = reader.generate_frame(predictors)
        n_rows = n_errors = 0
        #: whole-replay wall bound: a fleet that never becomes routable
        #: (every replica crash-looping) must fail the run loudly, not
        #: retry one row forever
        replay_deadline = time.monotonic() + float(
            cp.get("replayTimeoutS", 600.0))
        with profiler.phase(OpStep.SCORING):
            stack.start()
            try:
                conn = http.client.HTTPConnection(
                    "127.0.0.1", stack.port, timeout=60)
                for row in frame.iter_rows():
                    body = json.dumps(row, default=str)
                    while True:
                        if time.monotonic() > replay_deadline:
                            raise RuntimeError(
                                "scaleout replay exceeded "
                                f"{cp.get('replayTimeoutS', 600.0)}s "
                                f"(replicas: {stack.router.replicas()})"
                            )
                        try:
                            conn.request(
                                "POST", f"/score/{target}", body,
                                {"Content-Type": "application/json"})
                            resp = conn.getresponse()
                            resp.read()
                        except OSError:
                            conn.close()
                            time.sleep(0.05)
                            conn = http.client.HTTPConnection(
                                "127.0.0.1", stack.port, timeout=60)
                            continue
                        if resp.status == 503:
                            # router-level shed: wait out the hint and
                            # retry the SAME row — reporting load as an
                            # error slot would misread shed as loss
                            time.sleep(min(float(resp.headers.get(
                                "Retry-After", 0.05)), 0.5))
                            continue
                        break
                    n_rows += 1
                    if resp.status != 200:
                        n_errors += 1
                conn.close()
            finally:
                result["scaleout"] = stack.status()
                stack.stop()
        result["nRows"] = n_rows
        result["nErrors"] = n_errors
        result["rowsByModel"] = {target: n_rows}

    def _serve_fleet(self, params: OpParams, result: dict) -> None:
        """SERVE with ``customParams.modelDir``: replay the reader's rows
        through a multi-model ``FleetServer`` against
        ``customParams.defaultModel`` (required when more than one model
        is registered). The reader materializes exactly the target
        model's predictor columns, so per-row routing keys can't exist
        in this frame — per-request routing is the CLI's and the HTTP
        endpoint's job; the runner replay exercises one model's lane
        inside a live fleet (shared cache, neighbors registered)."""
        from transmogrifai_tpu.serving import FleetServer
        cp = dict(params.custom_params or {})
        queue_capacity = int(cp.get("queueCapacity", 1024))
        fleet = FleetServer(
            max_batch=int(cp.get("maxBatch", 256)),
            max_wait_ms=float(cp.get("maxWaitMs", 2.0)),
            queue_capacity=queue_capacity,
            strict=bool(cp.get("strict", True)),
            retries=int(cp.get("retries", 2)))
        entries = fleet.register_dir(cp["modelDir"])
        if not entries:
            raise ValueError(
                f"no saved models under modelDir {cp['modelDir']!r}")
        ids = fleet.registry.model_ids()
        target = cp.get("defaultModel") or \
            (ids[0] if len(ids) == 1 else None)
        if target is None:
            raise ValueError(
                f"modelDir holds {len(ids)} models ({', '.join(ids)}); "
                "customParams.defaultModel must name the replay target")
        ref = fleet.registry.get(target).model
        reader = (self.scoring_reader_factory(params)
                  if self.scoring_reader_factory else self.workflow.reader)
        predictors = [f for f in ref.raw_features if not f.is_response]
        frame = reader.generate_frame(predictors)
        n_rows = n_errors = 0
        window: list = []

        def _drain() -> None:
            nonlocal n_rows, n_errors
            for item in window:
                if isinstance(item, Exception):
                    n_errors += 1
                else:
                    try:
                        item.result()
                    except Exception:  # noqa: BLE001 — reported per slot below
                        n_errors += 1
                n_rows += 1
            window.clear()

        with profiler.phase(OpStep.SCORING):
            fleet.start()
            try:
                for row in frame.iter_rows():
                    try:
                        window.append(fleet.submit_blocking(target, row))
                    except KeyError as e:  # strict admission reject
                        window.append(e)
                    if len(window) >= queue_capacity:
                        _drain()
                _drain()
            finally:
                # snapshot BEFORE stop: stop() drops the lanes (and
                # their per-model metrics) so a restart builds fresh ones
                result["fleetMetrics"] = fleet.snapshot()
                fleet.stop()
        result["nRows"] = n_rows
        result["nErrors"] = n_errors
        result["rowsByModel"] = {target: n_rows}


def main(argv=None):
    ap = argparse.ArgumentParser("transmogrifai_tpu runner")
    ap.add_argument("--run-type", required=True, choices=RunTypes.ALL)
    ap.add_argument("--params", required=True, help="OpParams json path")
    ap.add_argument("--workflow", required=True,
                    help="import path to a module:attr WorkflowRunner")
    ap.add_argument("--checkpoint-dir", default=None,
                    help="resumable training: fitted DAG layers + the "
                         "selector sweep checkpoint here; re-running after "
                         "a crash resumes instead of refitting (train only)")
    ap.add_argument("--trace-out", default=None,
                    help="profile the run and write a Perfetto/"
                         "chrome://tracing JSON (host span tree + device "
                         "timeline) here")
    args = ap.parse_args(argv)
    import importlib
    mod, _, attr = args.workflow.partition(":")
    runner: WorkflowRunner = getattr(importlib.import_module(mod), attr)
    result = runner.run(args.run_type, OpParams.from_file(args.params),
                        checkpoint_dir=args.checkpoint_dir,
                        trace_out=args.trace_out)
    print(json.dumps(result, indent=2, default=str))
    return 0 if result.get("status") == "success" else 1


if __name__ == "__main__":
    sys.exit(main())
